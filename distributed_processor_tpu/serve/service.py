"""The continuous-batching execution service.

:class:`ExecutionService` is the in-process serving runtime over the
interpreter: any thread calls :meth:`~ExecutionService.submit` with one
compiled :class:`~..decoder.MachineProgram` and gets a
:class:`~.request.RequestHandle` back immediately; dispatcher threads
drain the queues, coalesce compatible requests into shape-bucketed
batches (``batcher.bucket_key``), run each batch through
:func:`~..sim.interpreter.simulate_multi_batch` — hitting the warm jit
cache keyed on the bucket SHAPE — and demux per-request stats back onto
the handles.  The classic continuous-batching contract (vLLM-style,
transplanted from token generation to shot execution):

* latency/throughput dial: a bucket dispatches when it reaches
  ``max_batch_programs`` or its oldest member has waited
  ``max_wait_ms``;
* admission control: a bounded queue (``max_queue``) makes overload a
  synchronous :class:`QueueFullError` at submit, not unbounded growth;
* isolation: ``fault_mode='strict'`` raises
  :class:`~..sim.interpreter.FaultError` on the OFFENDING request's
  handle only — batch-mates are fulfilled normally (per-request fault
  slices are checked after demux, never batch-wide);
* cancellation/deadlines honored at batch boundaries — the claim into
  a batch is the point of no return;
* graceful ``shutdown(drain=True)`` flushes everything queued, then
  joins every dispatcher.

Multi-device sharding (``devices=``): the service runs a POOL of
per-device executors, each owning its own coalescer queue, its own
dispatcher thread, and — because jit cache entries are per-device — its
own independent warm cache.  A bucket-affinity router pins each
``bucket_key`` to a home device (least-loaded at first sight, sticky
after) so a bucket's one-time compile is paid once and every later
dispatch of that bucket stays warm.  Work stealing migrates a ripened
batch to an idle device when the home is busy or backed up, accepting
the one-time compile on the thief (counted in ``stats()`` as a cold
hit and a steal).  The default ``devices=None`` is the single-executor
path with NO device pinning — byte-identical to the classic
single-device service, sharing the process default-device jit cache.

Bit-identity guarantee (tests/test_serve.py, test_serve_multidevice.py):
a demuxed result equals the solo ``simulate_batch`` run of the same
request under the same normalized cfg, per stat including
``fault_shots`` — REGARDLESS of which device ran it.  The multi path is
the generic engine vmapped over programs, each program's step counter
freezes independently; short requests are padded by replicating their
OWN shot rows and (under ``pad_programs``) batches are padded to a
power-of-two program count by replicating the last request — both inert
under deterministic execution, trimmed off in
:func:`~..sim.interpreter.demux_multi_batch`.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace

import numpy as np

import jax

from .. import isa
from ..decoder import machine_program_from_cmds, stack_machine_programs
from ..integrity import IntegrityError, diff_stats
from ..obs import FlightRecorder, Histogram, Tracer, write_chrome_trace
from ..ops.decode import as_decode_spec
from ..sim.interpreter import (ENGINES, InterpreterConfig, FaultError,
                               aot_batch_cached, aot_compile_batch,
                               demux_multi_batch, fault_shot_counts,
                               is_infrastructure_error, program_traits,
                               resolve_engine, simulate_batch,
                               simulate_multi_batch, simulate_rounds)
from ..utils import profiling
from .batcher import Coalescer, bucket_key, shed_exempt
from .bucketspec import BucketSpec
from .catalog import BucketCatalog
from .request import (DEFAULT_TENANT, CancelledError, DeadlineError,
                      ExecutorLostError, OverloadError, QueueFullError,
                      QuotaExceededError, Request, RequestHandle,
                      ServiceClosedError, ShutdownError)
from .stream import StreamKey, StreamSession
from .supervise import (HEALTH_LIVE, HEALTH_PROBING, HEALTH_QUARANTINED,
                        CircuitBreaker, RetryPolicy)

# service threads carry these prefixes so the test harness can detect
# leaked services (tests/conftest.py prints the junit-gated marker —
# tools/check_junit.py — when one survives a test); the supervision
# layer's threads share the 'dproc-serve' stem the conftest probe scans
DISPATCH_THREAD_PREFIX = 'dproc-serve-dispatch'
SUPERVISE_THREAD_PREFIX = 'dproc-serve-supervise'
CANARY_THREAD_PREFIX = 'dproc-serve-canary'
COMPILE_THREAD_PREFIX = 'dproc-serve-compile'
WARMUP_THREAD_PREFIX = 'dproc-serve-warmup'
SCRUB_THREAD_PREFIX = 'dproc-serve-scrub'

_SERVICE_SEQ = itertools.count()


def _normalize_cfg(cfg: InterpreterConfig, n_instr_bucket: int):
    """One request cfg -> (bucket-keyed jit cfg, strict flag).

    Budgets default from the BUCKET shape exactly like
    ``simulate_multi_batch`` derives them (content-derived budgets
    would fragment the buckets and retrace per ensemble); the engine
    selector is normalized away (multi path is generic-only) and
    'strict' is split out as the per-request host policy.
    """
    if cfg is None:
        cfg = InterpreterConfig(max_steps=2 * n_instr_bucket + 64,
                                max_pulses=n_instr_bucket + 2)
    if cfg.straightline or cfg.engine in ('straightline', 'block',
                                          'pallas', 'fused'):
        raise ValueError(
            'the execution service coalesces onto the multi-program '
            'generic engine; of the engine ladder (auto / generic / '
            'block / straightline / pallas / fused) the straightline, '
            'block, pallas and fused engines key on program content '
            'and cannot serve a shared batch (use singleton_engine= '
            'for 1-program fallback dispatch)')
    if cfg.opcode_histogram:
        raise ValueError(
            'opcode_histogram=True cannot be served: op_hist is summed '
            'over shot lanes inside the jit, so the shot-replication '
            'padding used to coalesce unequal shot counts would '
            'contaminate it (run simulate_batch directly instead)')
    if cfg.cores_axis is not None:
        raise ValueError(
            f'cores_axis={cfg.cores_axis!r} (sharded-cores execution) '
            'cannot serve: the service dispatches single-device '
            'simulate_batch batches and the cores-sharded fabric rides '
            'shard_map collectives over a live device mesh — it only '
            'runs via parallel.sweep.sharded_cores_simulate / '
            'parallel.run_cores_sweep')
    strict = cfg.fault_mode == 'strict'
    if cfg.fault_mode not in ('count', 'strict'):
        raise ValueError(
            f"fault_mode must be 'count' or 'strict'; got "
            f"{cfg.fault_mode!r}")
    if strict or cfg.straightline is None or cfg.engine is not None:
        cfg = replace(cfg, fault_mode='count', straightline=False,
                      engine=None)
    return cfg, strict


def _normalize_stream_cfg(cfg: InterpreterConfig, n_instr_bucket: int):
    """One stream-chunk cfg -> (dispatch cfg, strict flag).

    Streaming chunks never coalesce across programs — each dispatch is
    one session's ``simulate_rounds`` scan — so unlike
    :func:`_normalize_cfg` the engine selector SURVIVES (a stream may
    ride the content-keyed block/pallas rungs; only the physics-closed
    'fused' engine is rejected, exactly as every injected-bits entry
    rejects it).  ``rounds`` is normalized to 1 here — the ROUTING key
    must not fragment per chunk length; each chunk's dispatch cfg
    rebinds ``rounds`` to its own round count.  ``record_pulses`` is
    forced off: an R-round pulse record is R times the largest leaf in
    the result frame, which defeats incremental streaming (run
    ``simulate_rounds`` directly for record-level debugging)."""
    if cfg is None:
        cfg = InterpreterConfig(max_steps=2 * n_instr_bucket + 64,
                                max_pulses=n_instr_bucket + 2)
    if cfg.engine == 'fused':
        raise ValueError(
            "engine='fused' demodulates measurement windows in-kernel; "
            'streaming sessions dispatch injected-bits rounds scans — '
            'physics-closed execution only runs via '
            'sim.physics.run_physics_batch')
    if cfg.opcode_histogram:
        raise ValueError(
            'opcode_histogram=True cannot stream: op_hist is summed '
            'over shot lanes inside the jit (run simulate_rounds '
            'directly instead)')
    if cfg.cores_axis is not None:
        raise ValueError(
            f'cores_axis={cfg.cores_axis!r} (sharded-cores execution) '
            'cannot serve: the service dispatches single-device '
            'scans — mesh-wide rounds run via '
            'parallel.sweep.sharded_cores_rounds')
    strict = cfg.fault_mode == 'strict'
    if cfg.fault_mode not in ('count', 'strict'):
        raise ValueError(
            f"fault_mode must be 'count' or 'strict'; got "
            f"{cfg.fault_mode!r}")
    if strict or cfg.record_pulses or cfg.rounds != 1:
        cfg = replace(cfg, fault_mode='count', record_pulses=False,
                      rounds=1)
    return cfg, strict


def _pad_shots(arr: np.ndarray, n_shots: int) -> np.ndarray:
    """Pad the leading shot axis up to ``n_shots`` by replicating the
    last row — the inert-lane padding ``demux_multi_batch`` trims."""
    if arr.shape[0] == n_shots:
        return arr
    reps = np.repeat(arr[-1:], n_shots - arr.shape[0], axis=0)
    return np.concatenate([arr, reps], axis=0)


def _pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


class _TokenBucket:
    """Per-tenant admission rate limiter (docs/SERVING.md "Tenants").

    The bucket starts FULL at ``capacity`` (one burst's worth) and
    refills continuously at ``rate`` tokens/s; ``try_take`` is called
    under the service's lock, so no locking of its own."""

    __slots__ = ('rate', 'capacity', 'tokens', 't')

    def __init__(self, rate: float, capacity: float = None):
        self.rate = float(rate)
        self.capacity = float(capacity) if capacity is not None \
            else max(self.rate, 1.0)
        self.tokens = self.capacity
        self.t = time.monotonic()

    def try_take(self, n: float, now: float = None) -> bool:
        if now is None:
            now = time.monotonic()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self.t) * self.rate)
        self.t = now
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


def _tenant_zero() -> dict:
    """One tenant's fresh accounting block — the exact key set the
    frozen manifest in tests/test_obs.py pins (plus 'weight', merged
    in by stats())."""
    return {'queued': 0, 'submitted': 0, 'completed': 0, 'failed': 0,
            'shed': 0, 'quota_rejected': 0, 'shots': 0,
            'device_ms': 0.0, 'compile_ms': 0.0, 'bytes_wire': 0}


def _bucket_label(key: BucketSpec) -> str:
    """Human/JSON-able label for a bucket key: the shape part only
    (cores x instruction bucket).  Distinct cfg/geometry variants of
    the same shape share a label — the per-bucket compile stats answer
    "which SHAPES are hot", not "which exact executables"."""
    return f'c{key.n_cores}i{key.n_instr_bucket}'


def _bucket_compile_view(per: dict) -> dict:
    """One bucket's cold/warm classification with its dispatch latency
    split: mean timed cold/warm dispatch ms, and their difference as a
    per-bucket compile-cost estimate (a cold dispatch is
    trace+compile+execute, a warm one execute only — the difference is
    what AOT warmup deletes from first-request latency).  The means are
    None until a timed dispatch of that class lands (AOT warmups
    classify cold but dispatch nothing)."""
    cold_ms = (per['cold_s'] * 1e3 / per['cold_timed']
               if per['cold_timed'] else None)
    warm_ms = (per['warm_s'] * 1e3 / per['warm_timed']
               if per['warm_timed'] else None)
    est = (max(cold_ms - warm_ms, 0.0)
           if cold_ms is not None and warm_ms is not None else None)
    return {'cold': per['cold'], 'warm': per['warm'],
            'cold_ms_mean': cold_ms, 'warm_ms_mean': warm_ms,
            'compile_ms_est': est}


class _DeviceExecutor:
    """One device's slice of the service: its own coalescer queue, its
    own dispatcher thread, its own (per-device, hence independent) warm
    jit cache, and its own counters.  ``device=None`` means "do not pin"
    — the process default device, the classic single-device path.  All
    mutable state is guarded by the service's condition variable; the
    executor is a struct, the service owns the concurrency."""

    def __init__(self, svc: 'ExecutionService', idx: int, device,
                 max_batch_programs: int, max_wait_s: float,
                 breaker: CircuitBreaker, tenant_weights: dict = None):
        self.idx = idx
        self.device = device
        self.q = Coalescer(max_batch_programs, max_wait_s,
                           tenant_weights=tenant_weights)
        self.busy = False            # a batch is executing right now
        # -- supervision state (all under the service's cv) --------------
        self.health = HEALTH_LIVE
        self.breaker = breaker
        # (key, batch) currently executing: the supervisor's handle on
        # work to recover when the dispatch hangs or the thread dies
        self.inflight = None
        # wall-clock watchdog: absolute monotonic instant after which
        # the current dispatch counts as hung (None = no dispatch
        # running, or the watchdog is disabled)
        self.dispatch_deadline = None
        self.last_beat = time.monotonic()
        self.hangs = 0
        self.deaths = 0
        self.respawns = 0
        self.canary_ok = 0
        self.canary_fail = 0
        self.canary_thread = None
        # integrity fabric (docs/ROBUSTNESS.md "Integrity"): the last
        # audit's verdict (edge-triggers the integrity_violation
        # flight event) and the scrubber's consecutive-failure count
        self.integrity_bad = False
        self.scrub_fails = 0
        self.dispatches = 0
        self.programs_dispatched = 0
        self.occupancy = collections.Counter()          # batch size -> n
        self.engine_dispatches = collections.Counter()  # engine -> n
        self.steals = 0              # batches this executor stole
        self.stolen_from = 0         # batches stolen FROM this executor
        self.cold_compiles = 0
        self.warm_hits = 0
        # (bucket_key, shape signature) dispatched at least once on
        # this device: the host-side cold/warm compile classifier (the
        # jit cache itself keys on the same shapes, per device)
        self.seen = set()
        self.spawn_thread(svc)

    def spawn_thread(self, svc: 'ExecutionService') -> None:
        """(Re)create the dispatcher thread — __init__, and the
        supervisor's dead-thread respawn path (a fresh Thread object:
        a died Thread cannot be restarted)."""
        self.thread = threading.Thread(
            target=svc._dispatch_loop, args=(self,),
            name=f'{DISPATCH_THREAD_PREFIX}-{svc.name}-d{self.idx}',
            daemon=True)

    def label(self) -> str:
        return 'default' if self.device is None else str(self.device)


class ExecutionService:
    """In-process continuous-batching front end over the interpreter.

    Parameters
    ----------
    cfg:
        Default :class:`InterpreterConfig` for submissions that do not
        bring their own.  ``None`` (default) derives per-bucket budgets
        the same way ``simulate_multi_batch`` does.
    max_batch_programs:
        Coalescing ceiling — a bucket dispatches as soon as it holds
        this many requests.
    max_wait_ms:
        Coalescing deadline — a bucket with fewer requests dispatches
        once its oldest member has waited this long.  The
        latency/throughput dial: 0 approximates per-request dispatch,
        large values maximize occupancy.
    max_queue:
        Admission bound on TOTAL queued requests across buckets and
        devices; ``submit`` raises :class:`QueueFullError` beyond it.
    singleton_engine:
        Optional engine selector ('auto' / 'straightline' / 'block' /
        'pallas' / 'generic') for batches that end up with a single
        program: those gain nothing from the multi path, so they may
        ride :func:`simulate_batch` and the full engine ladder instead.
        Feedback programs (LUT-fabric fproc reads) dispatch on the
        fast rungs too — the timestamped fabric made their reads
        dispatch-granularity-invariant, so block/pallas serve them
        bit-identically (docs/PERF.md "Feedback on the fast
        engines"); tests/test_fproc_fast.py pins the dispatch.
        ('fused' is rejected at construction: the service dispatches
        injected-bits batches, and the fused measure-in-megastep engine
        only runs physics-closed.)
        Default None keeps everything on the one shared multi-program
        cache (the right call for compile-bound fleets).
    devices:
        How many executors the service shards across.  ``None``
        (default): ONE executor with no device pinning — the classic
        single-device service, regardless of how many devices the host
        advertises.  An int n / ``'all'``: one executor pinned to each
        of the first n / all local devices
        (:func:`~..parallel.mesh.serving_devices`).  Or an explicit
        sequence of jax devices.
    work_stealing:
        Allow an idle executor to migrate a ripened batch away from a
        busy or backed-up home device (one-time compile on the thief,
        counted in stats).  Default True; meaningless with one executor.
    pad_programs:
        Pad each multi-program batch to a power-of-two program count by
        replicating the last request (inert, trimmed at demux) so
        odd-sized remainders and stolen batches reuse the pow2-shaped
        executables instead of compiling one per batch size.  Default
        True.
    supervision:
        Run the supervisor thread: per-executor heartbeats, hang
        watchdog, dead-dispatcher detection + respawn, circuit-breaker
        quarantine with canary-probed re-admission (docs/ROBUSTNESS.md
        "serving-layer failures").  Default True.  With it off,
        infrastructure failures are still retried under
        ``retry_policy`` but a broken executor is never quarantined
        and a dead dispatcher is only cleaned up at shutdown.
    retry_policy:
        :class:`~.supervise.RetryPolicy` bounding how often an
        INFRASTRUCTURE failure (executor crash / hang / death — never
        :class:`FaultError`, validation or deadline errors) is retried
        on a healthy executor, with exponential backoff.  None
        (default) uses ``RetryPolicy()``; ``RetryPolicy(max_attempts=
        1)`` disables retrying.
    hang_timeout_s:
        Wall-clock watchdog on every device dispatch: one exceeding
        this is declared hung, its executor quarantined, its requests
        retried elsewhere (the straggler's eventual completion is
        discarded by the attempt token).  Default None = off — a cold
        XLA compile can legitimately take minutes, so only enable this
        on warmed-up services with a known service-time envelope.
    breaker_threshold / breaker_cooldown_ms:
        Circuit breaker: this many CONSECUTIVE infrastructure failures
        quarantine the executor; after the cooldown (doubling per
        re-trip, capped) a canary probe decides re-admission.
    max_est_wait_ms:
        Overload control: when the estimated queue service time (EWMA
        per-program batch time x queued programs / live executors)
        exceeds this bound, ``submit`` sheds the lowest-priority
        queued request (failing it with :class:`OverloadError`) to
        admit a higher-priority one, or rejects the submission
        outright; a request whose own ``deadline_ms`` provably cannot
        be met is rejected early instead of queueing to expire.
        Default None = off (the bounded queue / QueueFullError is
        then the only admission control, exactly as before).
    tenants:
        Per-tenant policy (docs/SERVING.md "Tenants"): a JSON-able
        dict ``{name: {'weight': 1.0, 'max_queued': None,
        'shots_per_s': None, 'shots_burst': None, 'compiles_per_s':
        None, 'compiles_burst': None}}``.  ``weight`` biases the
        deficit-round-robin fair queue; the quota/rate keys arm
        admission-time limits that raise the typed, non-retryable
        :class:`QuotaExceededError` (distinct from
        :class:`OverloadError`: "your contract forbids this", not
        "back off and retry").  Tenants not listed get weight 1.0 and
        no quotas — but ARE still metered.  Default None = no
        configured tenants; everything lands on the 'default' tenant.
    tenant_fair:
        Deficit-round-robin fair queueing across tenants in every
        coalescer (claim order interleaves tenants by weight instead
        of strict global FIFO; within a tenant, (priority, arrival)
        order is unchanged).  Default True; a single-tenant queue
        behaves identically either way.  Off restores the legacy
        global order — the ``tenant_isolation`` bench's baseline.

    ``warmup_catalog`` names a learned bucket catalog file
    (serve/catalog.py): every bucket this service dispatches is
    recorded there, and at construction any previously-recorded specs
    are replayed — AOT-compiled per device on a background
    ``dproc-serve-warmup-*`` thread (admission never blocks on it) —
    so a restarted service's first requests hit warm.  Progress is in
    ``stats()['warmup']``.  Default None = no catalog (explicit
    :meth:`warmup` calls still work).  Each construction opens a new
    catalog generation: specs not re-observed within
    ``catalog_max_age_runs`` generations are pruned, and the catalog
    is capped at ``catalog_max_specs`` entries (least-recently-seen
    evicted first) — a retired workload's buckets stop being
    recompiled at every startup.

    Observability (docs/OBSERVABILITY.md): ``trace_sample`` is the
    fraction of submissions that carry a per-request trace context
    recording typed lifecycle spans (queued / compile / coalesce-ripen
    / dispatch / execute / demux plus retry/steal/migration/park hops)
    readable via ``handle.trace()`` and exportable as Chrome Trace
    Event JSON via :meth:`dump_trace`.  Default 0.0 = off — the only
    per-request cost is the ``None`` context slot every handle already
    carries.  ``trace_keep`` bounds how many sampled traces are
    retained for export.  Every service also owns a
    :class:`~..obs.FlightRecorder` (``flight_events`` ring slots) that
    supervision, overload control, the chaos harness, and the compile
    cache record structured events into; it is dumped automatically on
    supervisor-detected executor deaths/hangs when ``flight_dump_dir``
    (or ``$DPROC_FLIGHT_DIR``) is set, and on demand via
    :meth:`dump_flight`.

    **Integrity fabric** (docs/ROBUSTNESS.md "Integrity"; all off by
    default, zero-cost on the hot path).  ``audit_sample=1/N``
    re-executes every Nth completed batch on a different engine (and
    different device when the pool has one) before delivery and
    bit-compares per stat, fault words included — a confirmed mismatch
    records an edge-triggered ``integrity_violation`` flight event and,
    under ``audit_mode='strict'``, fails the batch with a typed
    :class:`~..integrity.IntegrityError` (infrastructure-class: it
    retries, feeds the breaker, and never surfaces tainted bits).
    ``scrub_interval_s`` starts a background scrubber that replays the
    golden canary program per idle executor and routes
    ``breaker_threshold`` consecutive mismatches into the standard
    quarantine -> canary re-admission lifecycle.
    """

    def __init__(self, cfg: InterpreterConfig = None, *,
                 max_batch_programs: int = 16, max_wait_ms: float = 2.0,
                 max_queue: int = 256, singleton_engine: str = None,
                 name: str = None, devices=None,
                 work_stealing: bool = True, pad_programs: bool = True,
                 supervision: bool = True,
                 retry_policy: RetryPolicy = None,
                 hang_timeout_s: float = None,
                 breaker_threshold: int = 3,
                 breaker_cooldown_ms: float = 250.0,
                 supervise_interval_ms: float = 25.0,
                 max_est_wait_ms: float = None,
                 compile_cache=None, compile_workers: int = 2,
                 compile_cache_dir: str = None,
                 warmup_catalog: str = None,
                 catalog_max_specs: int = 512,
                 catalog_max_age_runs: int = 32,
                 trace_sample: float = 0.0, trace_keep: int = 1024,
                 flight_events: int = 512,
                 flight_dump_dir: str = None,
                 audit_sample: float = 0.0,
                 audit_mode: str = 'flag',
                 scrub_interval_s: float = None,
                 session_ttl_s: float = None,
                 tenants: dict = None,
                 tenant_fair: bool = True):
        if max_batch_programs < 1:
            raise ValueError('max_batch_programs must be >= 1')
        if max_queue < 1:
            raise ValueError('max_queue must be >= 1')
        if singleton_engine is not None and singleton_engine not in ENGINES:
            raise ValueError(
                f'singleton_engine must be one of {ENGINES} or None; '
                f'got {singleton_engine!r}')
        if singleton_engine == 'fused':
            raise ValueError(
                "singleton_engine='fused' (measure-in-megastep) cannot "
                'serve: the service dispatches injected-bits '
                'simulate_batch batches and the fused engine '
                'demodulates readout windows in-kernel — it only runs '
                'physics-closed via sim.physics.run_physics_batch')
        if cfg is not None and cfg.cores_axis is not None:
            raise ValueError(
                f'cores_axis={cfg.cores_axis!r} (sharded-cores '
                'execution) cannot serve: the service dispatches '
                'single-device simulate_batch batches and the '
                'cores-sharded fabric rides shard_map collectives over '
                'a live device mesh — it only runs via '
                'parallel.sweep.sharded_cores_simulate / '
                'parallel.run_cores_sweep')
        self._default_cfg = cfg
        self.max_batch_programs = max_batch_programs
        self.max_queue = max_queue
        self.singleton_engine = singleton_engine
        self.pad_programs = pad_programs
        self.name = name or f'svc{next(_SERVICE_SEQ)}'
        if devices is None:
            dev_list = [None]
        elif isinstance(devices, bool):
            raise ValueError('devices must be None, an int, "all", or '
                             'a sequence of jax devices')
        elif isinstance(devices, int):
            from ..parallel.mesh import serving_devices
            dev_list = serving_devices(devices)
        elif devices == 'all':
            from ..parallel.mesh import serving_devices
            dev_list = serving_devices()
        else:
            dev_list = list(devices)
            if not dev_list:
                raise ValueError('devices sequence must be non-empty')
        if hang_timeout_s is not None and hang_timeout_s <= 0:
            raise ValueError('hang_timeout_s must be positive or None')
        if max_est_wait_ms is not None and max_est_wait_ms <= 0:
            raise ValueError('max_est_wait_ms must be positive or None')
        if trace_sample < 0 or trace_sample > 1:
            raise ValueError('trace_sample must be in [0, 1]')
        if audit_sample < 0 or audit_sample > 1:
            raise ValueError('audit_sample must be in [0, 1]')
        if audit_mode not in ('flag', 'strict'):
            raise ValueError("audit_mode must be 'flag' or 'strict'; "
                             f'got {audit_mode!r}')
        if scrub_interval_s is not None and scrub_interval_s <= 0:
            raise ValueError('scrub_interval_s must be positive or '
                             'None')
        if session_ttl_s is not None and session_ttl_s <= 0:
            raise ValueError('session_ttl_s must be positive or None')
        # observability: per-request tracing (sampled) + flight
        # recorder — created before the executors so the first
        # dispatch can already emit into them
        self._tracer = Tracer(trace_sample, keep=trace_keep)
        self.flight_recorder = FlightRecorder(flight_events)
        self._flight_dump_dir = flight_dump_dir
        # submit→done latency in ms: per-service exact-percentile
        # window (stats() p50/p99, byte-compatible with the old
        # deque), mirrored into the process registry's fleet-wide
        # 'serve.latency_ms' histogram for Prometheus exposition
        self._latency_h = Histogram('serve.latency_ms', window=4096)
        self._supervision = bool(supervision)
        self._retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy()
        self._hang_timeout_s = hang_timeout_s
        self._supervise_interval_s = supervise_interval_ms / 1e3
        self._max_est_wait_s = None if max_est_wait_ms is None \
            else max_est_wait_ms / 1e3
        self._cv = threading.Condition()
        # -- tenant isolation fabric (docs/SERVING.md "Tenants") ---------
        # policy is parsed before the executors exist so every
        # coalescer shares ONE live weights dict (service-owned, read
        # under the cv like everything else)
        self._tenant_cfg = {}
        self._tenant_weights = {}
        for tname, spec in (tenants or {}).items():
            spec = dict(spec or {})
            w = float(spec.get('weight', 1.0))
            if w <= 0:
                raise ValueError(
                    f'tenant {tname!r}: weight must be > 0; got {w!r}')
            for k in ('max_queued', 'shots_per_s', 'compiles_per_s'):
                v = spec.get(k)
                if v is not None and v <= 0:
                    raise ValueError(
                        f'tenant {tname!r}: {k} must be positive or '
                        f'None; got {v!r}')
            self._tenant_cfg[str(tname)] = spec
            self._tenant_weights[str(tname)] = w
        self._tenant_fair = bool(tenant_fair)
        # name -> accounting block (_tenant_zero) — configured tenants
        # eagerly so stats()/fleet-status show them before first
        # traffic, everyone else lazily at first sight
        self._tenant_state = {t: _tenant_zero()
                              for t in self._tenant_cfg}
        self._tenant_shots_tb = {
            t: _TokenBucket(s['shots_per_s'], s.get('shots_burst'))
            for t, s in self._tenant_cfg.items()
            if s.get('shots_per_s') is not None}
        self._tenant_compile_tb = {
            t: _TokenBucket(s['compiles_per_s'],
                            s.get('compiles_burst'))
            for t, s in self._tenant_cfg.items()
            if s.get('compiles_per_s') is not None}
        self._executors = [
            _DeviceExecutor(self, i, d, max_batch_programs,
                            max_wait_ms / 1e3,
                            CircuitBreaker(breaker_threshold,
                                           breaker_cooldown_ms / 1e3),
                            tenant_weights=(self._tenant_weights
                                            if self._tenant_fair
                                            else None))
            for i, d in enumerate(dev_list)]
        self._stealing = bool(work_stealing) and len(self._executors) > 1
        self._home = {}                        # bucket_key -> executor idx
        self._home_counts = collections.Counter()
        self._seq = itertools.count()
        self._closing = False
        self._drain = True
        # stats (guarded by _cv's lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0          # FaultError / batch execution errors
        self._cancelled = 0
        self._expired = 0
        self._rejected = 0        # QueueFullError at admission
        self._dispatches = 0
        self._programs_dispatched = 0
        self._steals = 0
        self._warmups = 0
        # AOT warmup / learned-catalog state (docs/SERVING.md "cold
        # start & warmup"; guarded by _cv's lock)
        self._warmup_aot = 0           # executables actually compiled
        self._warmup_replayed = 0      # catalog specs replayed
        self._warmup_pending = 0       # (spec, device) replays still due
        self._warmup_thread = None
        self._occupancy = collections.Counter()   # batch size -> count
        self._engine_dispatches = collections.Counter()  # engine -> count
        # bucket label -> {'cold','warm'} counts plus timed dispatch
        # latency totals ({cold,warm}_s / _timed) for the compile-vs-
        # execute split stats() reports
        self._bucket_compiles = {}
        # -- supervision state (guarded by _cv's lock) -------------------
        # requests waiting out a retry backoff: (eligible_t, key, req),
        # pumped back into the queues by dispatchers and the supervisor
        self._parked = []
        self._stop_supervisor = False
        self._retries = 0
        self._retry_exhausted = 0
        self._shed = 0
        self._overload_rejected = 0
        self._breaker_trips = 0
        self._readmissions = 0
        self._executor_deaths = 0
        self._hangs = 0
        self._canary_ok = 0
        self._canary_fail = 0
        # EWMA of per-program batch service time (the overload
        # estimator's numerator); None until the first batch lands
        self._ewma_prog_s = None
        self._canary_mp = None         # lazily-built tiny probe program
        self._canary_ref = None        # first canary result: bit reference
        # -- streaming traffic class (docs/SERVING.md "Streaming
        # sessions"; guarded by _cv's lock).  _sessions maps an open
        # sid -> last-activity instant (the TTL sweep's input);
        # _stream_keys caches each session's sticky routing key;
        # _stream_live holds (handle, rounds) pairs so stats() can
        # count rounds in flight without walking every queue
        self._session_ttl_s = session_ttl_s
        self._stream_seq = itertools.count()
        self._sessions = {}
        self._stream_keys = {}
        self._stream_live = []
        self._stream_rounds_submitted = 0
        self._stream_rounds_served = 0
        self._stream_round_misses = 0
        self._stream_sessions_opened = 0
        self._stream_sessions_expired = 0
        # -- calibration traffic class (docs/SERVING.md "Calibration
        # sessions"; guarded by _cv's lock).  _calib_sessions maps an
        # open sid -> last-activity instant; sids draw from the same
        # sequence as streams so a sid names one session of either kind
        self._calib_sessions = {}
        self._calib_sessions_opened = 0
        self._calib_steps = 0
        self._calib_converged = 0
        self._calib_diverged = 0
        # -- integrity fabric (docs/ROBUSTNESS.md "Integrity") -----------
        # audit_sample=1/N re-executes every Nth completed batch on a
        # different engine (and device when the pool has one) before
        # delivery; the scrubber replays the canary program per
        # executor on an idle cadence.  Both feed the breaker /
        # quarantine machinery; all counters under _cv's lock.
        self._audit_sample = float(audit_sample)
        self._audit_every = 0 if audit_sample <= 0 \
            else max(1, round(1.0 / audit_sample))
        self._audit_mode = audit_mode
        self._audit_tick = 0
        self._audits = 0
        self._audit_mismatches = 0
        self._scrub_interval_s = scrub_interval_s
        self._scrubber_runs = 0
        self._scrubber_fail = 0
        self._integrity_quarantines = 0
        self._breaker_threshold = max(int(breaker_threshold), 1)
        # -- compile front door (guarded by _cv's lock where noted) ------
        if compile_workers < 1:
            raise ValueError('compile_workers must be >= 1')
        self._compile_cache = compile_cache
        if compile_cache is not None:
            # cache invalidations become flight-recorder events
            compile_cache.recorder = self.flight_recorder
        self._compile_cache_dir = compile_cache_dir
        self._compile_workers = compile_workers
        self._compile_pool = None      # lazily created on first submit_source
        self._source_submitted = 0
        self._source_handles = set()   # outer handles awaiting compile
        # learned bucket catalog: record every served bucket; replay
        # it at startup on a background thread so admission never
        # waits on warmup compiles
        self._catalog = None
        self._catalog_seen = set()
        replay_specs = []
        if warmup_catalog:
            self._catalog = BucketCatalog(
                warmup_catalog, max_specs=catalog_max_specs,
                max_age_runs=catalog_max_age_runs)
            # begin_run opens a new generation: aged-out / over-cap
            # specs are pruned before the replay set is taken
            replay_specs = self._catalog.begin_run()
            self._catalog_seen.update(s.identity() for s in replay_specs)
        for ex in self._executors:
            ex.thread.start()
        if replay_specs:
            self._warmup_pending = len(replay_specs) * len(self._executors)
            self._warmup_thread = threading.Thread(
                target=self._warmup_replay, args=(replay_specs,),
                name=f'{WARMUP_THREAD_PREFIX}-{self.name}',
                daemon=True)
            self._warmup_thread.start()
        self._supervisor = None
        if self._supervision:
            self._supervisor = threading.Thread(
                target=self._supervise_loop,
                name=f'{SUPERVISE_THREAD_PREFIX}-{self.name}',
                daemon=True)
            self._supervisor.start()
        self._scrubber = None
        if scrub_interval_s is not None:
            self._scrubber = threading.Thread(
                target=self._scrub_loop,
                name=f'{SCRUB_THREAD_PREFIX}-{self.name}',
                daemon=True)
            self._scrubber.start()

    # -- submission ------------------------------------------------------

    def traced_handle(self, trace_id: int) -> RequestHandle:
        """A fresh handle pre-bound to a forced :class:`TraceContext`:
        the fleet wire carries the ROUTER's sampling decision
        (deterministic on the trace id — docs/OBSERVABILITY.md "Fleet
        observability"), so the replica must trace exactly those
        requests regardless of its own sampling rate.  Pass it via
        ``_handle=`` so the submit path appends onto the same context
        the router will stitch."""
        h = RequestHandle()
        h._trace = self._tracer.start(trace_id)
        return h

    def submit(self, mp, meas_bits=None, *, shots: int = None,
               init_regs=None, cfg: InterpreterConfig = None,
               priority: int = 0, deadline_ms: float = None,
               fault_mode: str = None, tenant: str = None,
               _handle: RequestHandle = None):
        """Queue one program for execution; returns its
        :class:`RequestHandle` immediately.

        ``meas_bits`` is ``[n_shots, n_cores, n_meas]`` (or None with
        ``shots=`` for all-zero measurement feeds); ``init_regs`` is
        None, ``[n_cores, N_REGS]`` (shared across shots) or
        ``[n_shots, n_cores, N_REGS]``.  ``priority`` picks the lane
        (higher dispatches first); ``deadline_ms`` arms a
        relative-to-now deadline enforced at batch boundaries;
        ``fault_mode`` overrides the cfg's ('strict' raises
        :class:`FaultError` on THIS handle only, batch-mates are
        unaffected).  ``tenant`` names the submitting tenant
        (docs/SERVING.md "Tenants": fair queueing, quotas, metering);
        None lands on the 'default' tenant.
        """
        if meas_bits is None:
            if shots is None:
                raise ValueError('provide meas_bits or shots=')
            n_shots = int(shots)
            if n_shots < 1:
                raise ValueError('shots must be >= 1')
        else:
            meas_bits = np.asarray(meas_bits, np.int32)
            if meas_bits.ndim != 3 or meas_bits.shape[1] != mp.n_cores:
                raise ValueError(
                    f'meas_bits must be [n_shots, n_cores='
                    f'{mp.n_cores}, n_meas]; got '
                    f'{tuple(meas_bits.shape)}')
            if shots is not None and shots != meas_bits.shape[0]:
                raise ValueError(
                    f'shots={shots} contradicts meas_bits shot axis '
                    f'{meas_bits.shape[0]}')
            n_shots = meas_bits.shape[0]
            if n_shots < 1:
                raise ValueError('meas_bits must carry >= 1 shot')
        cfg = cfg if cfg is not None else self._default_cfg
        if fault_mode is not None:
            base = cfg if cfg is not None else InterpreterConfig(
                max_steps=2 * isa.shape_bucket(mp.n_instr) + 64,
                max_pulses=isa.shape_bucket(mp.n_instr) + 2)
            cfg = replace(base, fault_mode=fault_mode)
        cfg, strict = _normalize_cfg(cfg, isa.shape_bucket(mp.n_instr))
        if meas_bits is None:
            meas_bits = np.zeros((n_shots, mp.n_cores, cfg.max_meas),
                                 np.int32)
        elif meas_bits.shape[-1] != cfg.max_meas:
            # normalize the measurement width here (same truncate/zero-
            # pad as the interpreter's _pad_meas) so every member of a
            # bucket stacks into one [P, B, C, max_meas] tensor
            if meas_bits.shape[-1] > cfg.max_meas:
                meas_bits = meas_bits[..., :cfg.max_meas]
            else:
                meas_bits = np.pad(meas_bits, [
                    (0, 0), (0, 0),
                    (0, cfg.max_meas - meas_bits.shape[-1])])
        if init_regs is not None:
            init_regs = np.asarray(init_regs, np.int32)
            if init_regs.ndim == 2:
                init_regs = np.broadcast_to(
                    init_regs[None],
                    (n_shots,) + init_regs.shape).copy()
            if init_regs.ndim != 3 or init_regs.shape != (
                    n_shots, mp.n_cores, isa.N_REGS):
                raise ValueError(
                    f'init_regs must be [n_cores, {isa.N_REGS}] or '
                    f'[n_shots={n_shots}, n_cores={mp.n_cores}, '
                    f'{isa.N_REGS}]; got {tuple(init_regs.shape)}')
        deadline = None if deadline_ms is None \
            else time.monotonic() + deadline_ms / 1e3
        tenant = str(tenant) if tenant else DEFAULT_TENANT
        key = bucket_key(mp, cfg)
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
            if self._depth_locked() >= self.max_queue:
                self._rejected += 1
                profiling.counter_inc('serve.rejected')
                raise QueueFullError(
                    f'queue full ({self.max_queue} requests pending)')
            # tenant quota BEFORE overload control: an over-quota
            # submission must never shed another tenant's queued work
            self._admit_tenant_locked(tenant, shots=n_shots)
            self._admit_overload_locked(priority, deadline)
            # _handle: submit_source hands over the outer handle it
            # already returned to the tenant, so the dispatcher fulfills
            # that handle directly (no compile-pool thread ever blocks
            # on execution)
            hkw = {} if _handle is None else {'handle': _handle}
            req = Request(mp=mp, meas_bits=meas_bits,
                          init_regs=init_regs, cfg=cfg, strict=strict,
                          n_shots=n_shots, priority=priority,
                          deadline=deadline, seq=next(self._seq),
                          tenant=tenant, **hkw)
            self._open_tenant_locked(req)
            # tracing: submit_source already made the sampling call
            # for its outer handle; everything else draws here.  With
            # sampling off maybe_start returns None without allocating
            # — the handle's context slot stays None
            ctx = req.handle._trace if _handle is not None \
                else self._tracer.maybe_start()
            if ctx is not None:
                req.handle._trace = ctx
                ctx.instant('submit', t=req.submit_t, seq=req.seq,
                            bucket=key.label(), priority=priority)
            tgt = self._route_locked(key)
            if tgt is None:
                # every executor is quarantined/probing: park the
                # request; the first re-admission pumps it back in
                self._parked.append((time.monotonic(), key, req))
                if ctx is not None:
                    ctx.instant('park', reason='no-live-executor')
            else:
                tgt.q.push(key, req)
            self._submitted += 1
            profiling.counter_inc('serve.submitted')
            self._cv.notify_all()
        return req.handle

    # -- streaming traffic class (docs/SERVING.md "Streaming sessions") --

    def open_stream(self, mp, *, cfg: InterpreterConfig = None,
                    decode=None, round_deadline_ms: float = None,
                    priority: int = 0, fault_mode: str = None,
                    tenant: str = None) -> StreamSession:
        """Open a long-lived streaming session for ``mp``: returns a
        :class:`~.stream.StreamSession` whose ``submit_rounds`` chunks
        dispatch as device-resident R-round scans
        (:func:`~..sim.interpreter.simulate_rounds`) with ``decode``
        (a :class:`~..ops.decode.DecodeSpec`) run in-loop.  All chunks
        of a session share one sticky routing key, so the session
        lives on a home executor with a warm scan executable;
        ``round_deadline_ms`` arms each chunk with ``rounds x`` that
        budget, honored at scan-chunk boundaries."""
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
            sid = next(self._stream_seq)
            self._sessions[sid] = time.monotonic()
            self._stream_sessions_opened += 1
        profiling.counter_inc('serve.stream.sessions_opened')
        self.flight_recorder.record('stream_open', sid=sid)
        return StreamSession(self, mp, sid, cfg=cfg, decode=decode,
                             round_deadline_ms=round_deadline_ms,
                             priority=priority, fault_mode=fault_mode,
                             tenant=tenant)

    def close_stream(self, sid: int) -> bool:
        """Deregister an open session (idempotent; the TTL sweep and
        the session's own ``close`` both land here).  Outstanding
        chunk handles are unaffected — they are ordinary requests and
        complete or fail on their own lifecycle."""
        with self._cv:
            known = self._sessions.pop(sid, None) is not None
            self._stream_keys.pop(sid, None)
        if known:
            profiling.counter_inc('serve.stream.sessions_closed')
        return known

    # -- calibration traffic class (docs/SERVING.md "Calibration
    # sessions") ---------------------------------------------------------

    def open_calibration(self, *, knob: str = 'amplitude',
                         tenant: str = None, priority: int = 0):
        """Open a calibration session: returns a
        :class:`~..calib.session.CalibrationSession` whose per-step
        candidate programs ride the ordinary ``submit_source`` front
        door under the session's tenant identity.  The service counts
        the session's steps and its terminal transition
        (``stats()['calibration']``, ``serve.calib.*`` counters);
        convergence/divergence land in the flight recorder."""
        from ..calib.session import CalibrationSession
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
            sid = next(self._stream_seq)
            self._calib_sessions[sid] = time.monotonic()
            self._calib_sessions_opened += 1
        profiling.counter_inc('serve.calib.sessions_opened')
        self.flight_recorder.record('calib_open', sid=sid, knob=knob)
        return CalibrationSession(self, sid, knob=knob, tenant=tenant,
                                  priority=priority)

    def close_calibration(self, sid: int) -> bool:
        """Deregister an open calibration session (idempotent).
        Outstanding candidate handles are unaffected — they are
        ordinary requests and complete on their own lifecycle."""
        with self._cv:
            known = self._calib_sessions.pop(sid, None) is not None
        if known:
            profiling.counter_inc('serve.calib.sessions_closed')
        return known

    def calib_event(self, sid: int, kind: str, **info) -> None:
        """Observability sink for a session's loop: ``kind`` is
        ``'step' | 'converged' | 'diverged'``.  Steps advance the
        session's activity instant and the step counters; the terminal
        kinds additionally land in the flight recorder (a diverged
        calibration is an incident-timeline event)."""
        if kind not in ('step', 'converged', 'diverged'):
            raise ValueError(
                f"calib event kind must be 'step', 'converged' or "
                f"'diverged'; got {kind!r}")
        with self._cv:
            if kind == 'step':
                self._calib_steps += 1
            elif kind == 'converged':
                self._calib_converged += 1
            else:
                self._calib_diverged += 1
            if sid in self._calib_sessions:
                self._calib_sessions[sid] = time.monotonic()
        profiling.counter_inc(f'serve.calib.{kind}s' if kind == 'step'
                              else f'serve.calib.{kind}')
        if kind != 'step':
            self.flight_recorder.record(f'calib_{kind}', sid=sid,
                                        **info)

    def submit_rounds(self, mp, meas_bits, *, init_regs=None,
                      cfg: InterpreterConfig = None, decode=None,
                      priority: int = 0, deadline_ms: float = None,
                      round_deadline_ms: float = None,
                      fault_mode: str = None, stream: int = None,
                      tenant: str = None,
                      _handle: RequestHandle = None):
        """Queue one R-round streaming chunk; returns its
        :class:`RequestHandle` immediately.  ``meas_bits`` is
        ``[rounds, n_shots, n_cores, n_meas]``; the dispatcher runs
        the whole chunk as ONE :func:`~..sim.interpreter.
        simulate_rounds` scan (with ``decode`` in-loop), so the result
        is the rounds pytree — leading round axis per leaf.

        ``stream`` binds the chunk to an open session (sticky home
        executor, TTL accounting); None submits a detached one-shot
        chunk under its own fresh sid.  ``round_deadline_ms`` arms a
        ``rounds x round_deadline_ms`` chunk deadline (mutually
        exclusive with ``deadline_ms``); a chunk expiring counts every
        round it carried as a round-deadline miss.  Retry, steal,
        priority and overload semantics are exactly :meth:`submit`'s.
        """
        meas_bits = np.asarray(meas_bits, np.int32)
        if meas_bits.ndim != 4 or meas_bits.shape[2] != mp.n_cores:
            raise ValueError(
                f'meas_bits must be [rounds, n_shots, n_cores='
                f'{mp.n_cores}, n_meas]; got {tuple(meas_bits.shape)}')
        rounds, n_shots = int(meas_bits.shape[0]), int(meas_bits.shape[1])
        if rounds < 1:
            raise ValueError('meas_bits must carry >= 1 round')
        if n_shots < 1:
            raise ValueError('meas_bits must carry >= 1 shot')
        if deadline_ms is not None and round_deadline_ms is not None:
            raise ValueError(
                'pass deadline_ms or round_deadline_ms, not both')
        cfg = cfg if cfg is not None else self._default_cfg
        if fault_mode is not None:
            base = cfg if cfg is not None else InterpreterConfig(
                max_steps=2 * isa.shape_bucket(mp.n_instr) + 64,
                max_pulses=isa.shape_bucket(mp.n_instr) + 2)
            cfg = replace(base, fault_mode=fault_mode)
        cfg, strict = _normalize_stream_cfg(
            cfg, isa.shape_bucket(mp.n_instr))
        if decode is not None:
            decode = as_decode_spec(decode)
            bad = [c for c in decode.cores
                   if not 0 <= c < mp.n_cores]
            if bad:
                raise ValueError(
                    f'decode.cores {bad} out of range for n_cores='
                    f'{mp.n_cores}')
        if meas_bits.shape[-1] != cfg.max_meas:
            if meas_bits.shape[-1] > cfg.max_meas:
                meas_bits = meas_bits[..., :cfg.max_meas]
            else:
                meas_bits = np.pad(meas_bits, [
                    (0, 0), (0, 0), (0, 0),
                    (0, cfg.max_meas - meas_bits.shape[-1])])
        if init_regs is not None:
            init_regs = np.asarray(init_regs, np.int32)
            if init_regs.ndim == 2:
                init_regs = np.broadcast_to(
                    init_regs[None],
                    (n_shots,) + init_regs.shape).copy()
            if init_regs.ndim != 3 or init_regs.shape != (
                    n_shots, mp.n_cores, isa.N_REGS):
                raise ValueError(
                    f'init_regs must be [n_cores, {isa.N_REGS}] or '
                    f'[n_shots={n_shots}, n_cores={mp.n_cores}, '
                    f'{isa.N_REGS}]; got {tuple(init_regs.shape)}')
        if round_deadline_ms is not None:
            deadline_ms = rounds * round_deadline_ms
        deadline = None if deadline_ms is None \
            else time.monotonic() + deadline_ms / 1e3
        # the chunk's dispatch cfg rebinds rounds; the ROUTING key
        # keeps the rounds=1 normalized cfg so every chunk of the
        # session shares one sticky key regardless of chunk length
        rcfg = replace(cfg, rounds=rounds)
        tenant = str(tenant) if tenant else DEFAULT_TENANT
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
            if stream is None:
                sid = next(self._stream_seq)
            else:
                sid = stream
                if sid not in self._sessions:
                    raise ValueError(f'stream {sid} is not open '
                                     f'(expired or closed)')
                self._sessions[sid] = time.monotonic()
            key = self._stream_keys.get(sid)
            if key is None:
                key = StreamKey(sid=sid, n_cores=mp.n_cores,
                                n_instr_bucket=isa.shape_bucket(
                                    mp.n_instr), cfg=cfg)
                self._stream_keys[sid] = key
            if self._depth_locked() >= self.max_queue:
                self._rejected += 1
                profiling.counter_inc('serve.rejected')
                raise QueueFullError(
                    f'queue full ({self.max_queue} requests pending)')
            # shot-rounds are the billed unit of a streaming chunk:
            # an R-round B-shot chunk draws R x B from the bucket
            self._admit_tenant_locked(tenant, shots=rounds * n_shots)
            self._admit_overload_locked(priority, deadline)
            hkw = {} if _handle is None else {'handle': _handle}
            req = Request(mp=mp, meas_bits=meas_bits,
                          init_regs=init_regs, cfg=rcfg, strict=strict,
                          n_shots=n_shots, priority=priority,
                          deadline=deadline, seq=next(self._seq),
                          rounds=rounds, decode=decode, sid=sid,
                          tenant=tenant, **hkw)
            self._open_tenant_locked(req)
            ctx = req.handle._trace if _handle is not None \
                else self._tracer.maybe_start()
            if ctx is not None:
                req.handle._trace = ctx
                ctx.instant('submit', t=req.submit_t, seq=req.seq,
                            bucket=key.label(), priority=priority,
                            rounds=rounds)
            tgt = self._route_locked(key)
            if tgt is None:
                self._parked.append((time.monotonic(), key, req))
                if ctx is not None:
                    ctx.instant('park', reason='no-live-executor')
            else:
                tgt.q.push(key, req)
            self._submitted += 1
            self._stream_rounds_submitted += rounds
            self._stream_live.append((req.handle, rounds))
            profiling.counter_inc('serve.submitted')
            profiling.counter_inc('serve.stream.rounds_submitted',
                                  rounds)
            self._cv.notify_all()
        return req.handle

    def _expire_sessions_locked(self, now: float) -> None:
        """TTL sweep (supervisor tick): an open session idle past
        ``session_ttl_s`` is deregistered — ``session_expired`` flight
        event, ``serve.stream.sessions_expired`` counter — so an
        abandoned producer cannot pin its home-executor affinity
        forever.  Outstanding chunks complete normally; the session
        object's next ``submit_rounds`` is rejected."""
        if self._session_ttl_s is None or not self._sessions:
            return
        dead = [sid for sid, t in self._sessions.items()
                if now - t > self._session_ttl_s]
        for sid in dead:
            del self._sessions[sid]
            self._stream_keys.pop(sid, None)
            self._stream_sessions_expired += 1
            profiling.counter_inc('serve.stream.sessions_expired')
            self.flight_recorder.record(
                'session_expired', sid=sid,
                ttl_s=self._session_ttl_s)

    # -- the compile front door ------------------------------------------

    @property
    def compile_cache(self):
        """The service's :class:`~..compilecache.CompileCache` (created
        on first touch unless one was injected at construction)."""
        with self._cv:
            if self._compile_cache is None:
                from ..compilecache import CompileCache
                self._compile_cache = CompileCache(
                    cache_dir=self._compile_cache_dir)
                self._compile_cache.recorder = self.flight_recorder
            return self._compile_cache

    def submit_source(self, program, qchip, *, shots: int = None,
                      meas_bits=None, init_regs=None,
                      cfg: InterpreterConfig = None, priority: int = 0,
                      deadline_ms: float = None, fault_mode: str = None,
                      n_qubits: int = 8, pad_to: int = None,
                      channel_configs=None, fpga_config=None,
                      compiler_flags=None, tenant: str = None,
                      _handle: RequestHandle = None):
        """Submit PROGRAM SOURCE — a dict-instruction list or OpenQASM 3
        text — instead of a pre-built MachineProgram; returns a
        :class:`RequestHandle` immediately.

        The program compiles-or-hits through the service's content-
        addressed :class:`~..compilecache.CompileCache` on a small
        compile worker pool (``compile_workers``), so compilation never
        blocks the dispatcher threads; the compiled request then flows
        through :meth:`submit` onto the SAME handle.  Results are
        bit-identical to ``compile_to_machine`` + ``submit``
        (tests/test_compilecache.py pins it).  Failures surface typed
        on the handle: :class:`~..decoder.ProgramValidationError` with
        ``(core, instr)`` coordinates for a malformed program,
        :class:`QueueFullError`/:class:`OverloadError` at admission,
        :class:`ShutdownError` when the service closes first.
        ``deadline_ms`` arms at dispatch (compile time is not charged
        against it).
        """
        # _handle: the fleet wire hands over a pre-made handle (and
        # possibly a forced trace context carrying the router's
        # sampling decision); everything else gets a fresh handle and
        # draws the sampling decision here, at the tenant-visible
        # boundary, so the compile span lands on the same context the
        # dispatch spans will
        handle = _handle if _handle is not None else RequestHandle()
        ctx = handle._trace if _handle is not None \
            else self._tracer.maybe_start()
        if ctx is not None:
            handle._trace = ctx
            ctx.instant('submit_source')
        tenant = str(tenant) if tenant else DEFAULT_TENANT
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
            # compile-rate gate at the front door, SYNCHRONOUS: an
            # over-rate tenant is told no before a compile worker is
            # ever tied up on its program
            self._admit_tenant_locked(tenant, compile_sub=True)
            if self._compile_pool is None:
                self._compile_pool = ThreadPoolExecutor(
                    max_workers=self._compile_workers,
                    thread_name_prefix=(
                        f'{COMPILE_THREAD_PREFIX}-{self.name}'))
            pool = self._compile_pool
            self._source_submitted += 1
            self._source_handles.add(handle)
        cache = self.compile_cache

        def _compile_and_submit():
            try:
                if handle.cancelled():
                    return
                t_c = time.monotonic()
                mp, _status, _key = cache.get_or_compile(
                    program, qchip, channel_configs=channel_configs,
                    fpga_config=fpga_config,
                    compiler_flags=compiler_flags, n_qubits=n_qubits,
                    pad_to=pad_to)
                t_done = time.monotonic()
                # compile-ms is billed to the submitting tenant even
                # on a cache hit (the hit costs ~0 ms — the meter is
                # wall time spent, not a flat fee)
                self._meter_compile(tenant, (t_done - t_c) * 1e3)
                if handle._trace is not None:
                    handle._trace.span('compile', t_c, t_done,
                                       status=_status)
                self.submit(mp, meas_bits, shots=shots,
                            init_regs=init_regs, cfg=cfg,
                            priority=priority, deadline_ms=deadline_ms,
                            fault_mode=fault_mode, tenant=tenant,
                            _handle=handle)
            except BaseException as e:
                handle._fail(e)
            finally:
                with self._cv:
                    self._source_handles.discard(handle)

        try:
            pool.submit(_compile_and_submit)
        except RuntimeError as e:
            # pool shut down between our check and the enqueue
            with self._cv:
                self._source_handles.discard(handle)
            handle._fail(ServiceClosedError(
                f'service {self.name!r} is shut down'))
            raise ServiceClosedError(
                f'service {self.name!r} is shut down') from e
        profiling.counter_inc('serve.source_submitted')
        return handle

    # -- tenant isolation fabric (docs/SERVING.md "Tenants") -------------

    def _tenant_locked(self, tenant: str) -> dict:
        ts = self._tenant_state.get(tenant)
        if ts is None:
            ts = self._tenant_state[tenant] = _tenant_zero()
        return ts

    def _admit_tenant_locked(self, tenant: str, *, shots: int = 0,
                             compile_sub: bool = False) -> None:
        """Admission-time quota gate: max queued requests, shots/s and
        compile-submissions/s token buckets.  Raises the typed,
        non-retryable :class:`QuotaExceededError`; tenants with no
        configured policy pass through untouched (still metered)."""
        spec = self._tenant_cfg.get(tenant)
        if spec is None:
            return
        ts = self._tenant_locked(tenant)
        mq = spec.get('max_queued')
        if mq is not None and not compile_sub and ts['queued'] >= mq:
            self._reject_quota_locked(
                tenant, ts, f'max_queued={mq} requests already pending')
        if shots:
            tb = self._tenant_shots_tb.get(tenant)
            if tb is not None and not tb.try_take(shots):
                self._reject_quota_locked(
                    tenant, ts,
                    f'shots/s rate limit ({tb.rate:g}/s, burst '
                    f'{tb.capacity:g}) cannot cover {shots} shots')
        if compile_sub:
            ctb = self._tenant_compile_tb.get(tenant)
            if ctb is not None and not ctb.try_take(1):
                self._reject_quota_locked(
                    tenant, ts,
                    f'compile-submissions/s rate limit '
                    f'({ctb.rate:g}/s, burst {ctb.capacity:g}) '
                    f'exhausted')

    def _reject_quota_locked(self, tenant: str, ts: dict,
                             why: str) -> None:
        ts['quota_rejected'] += 1
        profiling.counter_inc(f'tenant.{tenant}.quota_rejected')
        self.flight_recorder.record('quota_reject', tenant=tenant,
                                    reason=why)
        raise QuotaExceededError(
            f'tenant {tenant!r} over quota: {why} — quota rejections '
            f'are not retryable (distinct from OverloadError '
            f'backpressure; see docs/SERVING.md "Tenants")')

    def _open_tenant_locked(self, req: Request) -> None:
        """Open one request's tenant accounting: count the submission
        and install the exactly-once resolution callback that closes
        it (outstanding count down, completed/failed up) on WHATEVER
        path resolves the handle — fulfill, fail, shed, deadline, or
        a submitter-side cancel that never re-enters the service."""
        tenant = req.tenant
        ts = self._tenant_locked(tenant)
        ts['submitted'] += 1
        profiling.counter_inc(f'tenant.{tenant}.submitted')

        def _done(ok: bool, _ts=ts, _t=tenant):
            with self._cv:
                _ts['queued'] -= 1
                _ts['completed' if ok else 'failed'] += 1
            profiling.counter_inc(
                f'tenant.{_t}.completed' if ok
                else f'tenant.{_t}.failed')

        if req.handle._set_on_done(_done):
            ts['queued'] += 1
        # else: the handle resolved before admission finished (e.g. a
        # submit_source handle cancelled mid-compile) — the callback
        # will never fire, so the outstanding count never opened

    def _tenant_pressure_locked(self) -> dict:
        """How far over its admission quota each tenant is (queued /
        max_queued) — the shed selector's primary rank: the most-
        over-quota tenant's newest work is evicted first.  Tenants
        with no max_queued quota carry no pressure (0.0 implied)."""
        out = {}
        for t, ts in self._tenant_state.items():
            mq = (self._tenant_cfg.get(t) or {}).get('max_queued')
            if mq:
                out[t] = ts['queued'] / float(mq)
        return out

    def _meter_compile(self, tenant: str, ms: float) -> None:
        with self._cv:
            self._tenant_locked(tenant)['compile_ms'] += ms
        profiling.counter_inc(f'tenant.{tenant}.compile_ms',
                              int(round(ms)))

    def meter_wire(self, tenant: str, nbytes: int) -> None:
        """Billing-grade bytes-on-wire metering hook for the fleet
        transport: the replica server calls this with each submit-op
        request frame's size and its response frame's size, attributed
        to the frame's tenant (docs/OBSERVABILITY.md)."""
        tenant = str(tenant) if tenant else DEFAULT_TENANT
        nbytes = int(nbytes)
        with self._cv:
            self._tenant_locked(tenant)['bytes_wire'] += nbytes
        profiling.counter_inc(f'tenant.{tenant}.bytes_wire', nbytes)

    def _admit_overload_locked(self, priority: int, deadline) -> None:
        """Overload control (``max_est_wait_ms``): estimate how long
        the queue will take to serve, reject a submission that provably
        cannot meet its own deadline, and above the bound either shed
        the lowest-priority queued request to make room or reject the
        newcomer (docs/ROBUSTNESS.md "serving-layer failures")."""
        if self._max_est_wait_s is None:
            return
        est_s = self._est_wait_s_locked()
        if est_s is None:       # no completed batch yet: no estimate
            return
        now = time.monotonic()
        if deadline is not None and now + est_s >= deadline:
            self._overload_rejected += 1
            profiling.counter_inc('serve.overload_rejected')
            self.flight_recorder.record(
                'overload_reject', reason='deadline-unmeetable',
                est_wait_ms=round(est_s * 1e3, 3))
            raise OverloadError(
                f'deadline cannot be met: estimated queue wait '
                f'{est_s * 1e3:.1f} ms exceeds the '
                f'{(deadline - now) * 1e3:.1f} ms remaining — '
                f'rejected at admission instead of queueing to expire')
        if est_s <= self._max_est_wait_s:
            return
        if self._shed_locked(priority) is None:
            self._overload_rejected += 1
            profiling.counter_inc('serve.overload_rejected')
            self.flight_recorder.record(
                'overload_reject', reason='nothing-to-shed',
                est_wait_ms=round(est_s * 1e3, 3))
            raise OverloadError(
                f'overloaded: estimated queue wait {est_s * 1e3:.1f} '
                f'ms exceeds max_est_wait_ms='
                f'{self._max_est_wait_s * 1e3:g} and nothing of lower '
                f'priority is queued to shed')

    def _est_wait_s_locked(self):
        """Estimated service time of the current backlog: queued
        programs x EWMA per-program batch time / live executors.
        None until the first batch completes."""
        if self._ewma_prog_s is None:
            return None
        live = sum(1 for ex in self._executors
                   if ex.health == HEALTH_LIVE) or 1
        return self._depth_locked() * self._ewma_prog_s / live

    def _shed_locked(self, below_priority: int):
        """Evict the globally most-sheddable queued/parked request
        strictly below ``below_priority`` — the most-over-quota
        tenant's newest work first (``_tenant_pressure_locked``), then
        lowest priority, newest arrival (least invested) — failing it
        with :class:`OverloadError`.  Stream chunks and service-
        internal work are exempt (``batcher.shed_exempt``): another
        tenant's admission pressure never breaks a live session or an
        audit.  Returns the shed request or None."""
        pressure = self._tenant_pressure_locked()
        best = None                      # (rank, executor-or-None, key, req)
        for ex in self._executors:
            cand = ex.q.shed_candidate(below_priority, pressure)
            if cand is None:
                continue
            key, req = cand
            rank = (-pressure.get(req.tenant, 0.0),
                    req.priority, -req.seq)
            if best is None or rank < best[0]:
                best = (rank, ex, key, req)
        for _, key, req in self._parked:
            if req.priority >= below_priority or req.handle.done() \
                    or shed_exempt(req):
                continue
            rank = (-pressure.get(req.tenant, 0.0),
                    req.priority, -req.seq)
            if best is None or rank < best[0]:
                best = (rank, None, key, req)
        if best is None:
            return None
        _, ex, key, req = best
        if ex is None:
            self._parked = [it for it in self._parked
                            if it[2] is not req]
        elif not ex.q.remove(key, req):
            return None
        if req.handle._fail(OverloadError(
                f'shed under overload: estimated queue wait exceeds '
                f'max_est_wait_ms={self._max_est_wait_s * 1e3:g} and '
                f'a higher-priority request arrived')):
            self._shed += 1
            profiling.counter_inc('serve.shed')
            ts = self._tenant_locked(req.tenant)
            ts['shed'] += 1
            profiling.counter_inc(f'tenant.{req.tenant}.shed')
            self.flight_recorder.record('shed', req=req.seq,
                                        priority=req.priority,
                                        tenant=req.tenant)
        return req

    # -- routing / stealing ----------------------------------------------

    def _depth_locked(self) -> int:
        return sum(len(ex.q) for ex in self._executors) \
            + len(self._parked)

    def _route_locked(self, key) -> _DeviceExecutor:
        """Bucket-affinity router: the first sighting of a bucket pins
        it to the least-loaded LIVE executor (queue depth, then how
        many home buckets it already carries, then index —
        deterministic); every later submission of the bucket lands on
        the same home so its warm per-device jit cache stays hot.  A
        home that got quarantined re-pins to a live peer; None when no
        executor is live (the caller parks the request)."""
        idx = self._home.get(key)
        if idx is not None \
                and self._executors[idx].health == HEALTH_LIVE:
            return self._executors[idx]
        live = [ex for ex in self._executors
                if ex.health == HEALTH_LIVE]
        if not live:
            return None
        if idx is not None:
            self._home_counts[idx] -= 1
        ex = min(live, key=lambda e: (len(e.q),
                                      self._home_counts[e.idx],
                                      e.idx))
        self._home[key] = ex.idx
        self._home_counts[ex.idx] += 1
        return ex

    def _try_steal_locked(self, thief: _DeviceExecutor, now: float,
                          flush: bool = False) -> bool:
        """Migrate one ripened batch from the deepest eligible victim
        queue into ``thief``'s.  A victim is eligible when it has a
        ripe bucket it cannot serve promptly: it is mid-execution, or
        more than one bucket ripened at once (or the service is
        draining, when any backlog is fair game).  Returns True when
        requests actually moved; the thief's own pop_batch then claims
        them (``absorb`` re-ran the deadline/cancel checks — a stolen
        request never outlives its deadline silently)."""
        best = None
        for v in self._executors:
            if v is thief or len(v.q) == 0:
                continue
            ripe = v.q.ripe_keys(now, flush=flush)
            if not ripe:
                continue
            if not (flush or v.busy or len(ripe) > 1):
                continue
            if best is None or len(v.q) > len(best[0].q):
                best = (v, ripe[0])
        if best is None:
            return False
        victim, key = best
        reqs = victim.q.migrate_bucket(key, self.max_batch_programs)
        if not reqs:
            return False
        victim.stolen_from += 1
        thief.steals += 1
        self._steals += 1
        profiling.counter_inc('serve.steals')
        self.flight_recorder.record('steal', victim=victim.label(),
                                    thief=thief.label(),
                                    bucket=key.label(), n=len(reqs))
        if self._tracer.enabled:
            for r in reqs:
                if r.handle._trace is not None:
                    r.handle._trace.instant('steal',
                                            src=victim.label(),
                                            dst=thief.label())
        expired = thief.q.absorb(key, reqs, now)
        self._count_expired_locked(expired)
        return True

    def _count_expired_locked(self, expired) -> None:
        if expired:
            self._expired += len(expired)
            profiling.counter_inc('serve.expired', len(expired))
            # a streaming chunk expiring misses EVERY round it carried
            # (per-round deadlines are honored at scan-chunk
            # boundaries — the whole chunk is the deadline unit)
            missed = sum(r.rounds for r in expired
                         if r.rounds is not None)
            if missed:
                self._stream_round_misses += missed
                profiling.counter_inc(
                    'serve.stream.round_deadline_misses', missed)

    # -- supervision -----------------------------------------------------

    def _pump_parked_locked(self, now: float, flush: bool = False):
        """Move parked retries whose backoff elapsed back into a live
        executor's queue (forced: they already waited out the latency
        dial once).  Deadlines are re-checked here — a parked request
        never outlives its ``deadline_ms`` silently — and with no live
        executor the request stays parked until a re-admission (or, on
        a draining shutdown, drains through ANY executor)."""
        if not self._parked:
            return
        keep = []
        for item in self._parked:
            t, key, req = item
            if req.handle.done():
                if req.handle.cancelled():
                    self._cancelled += 1
                continue
            if not flush and t > now:
                keep.append(item)
                continue
            if req.expired(now):
                if req.handle._fail(DeadlineError(
                        f'deadline passed while parked for retry '
                        f'({now - req.submit_t:.3f} s after '
                        f'submission)')):
                    self._count_expired_locked([req])
                continue
            tgt = self._route_locked(key)
            if tgt is None and flush:
                tgt = min(self._executors,
                          key=lambda e: (len(e.q), e.idx))
            if tgt is None:
                keep.append(item)
                continue
            if req.handle._trace is not None:
                req.handle._trace.instant('unpark',
                                          executor=tgt.label())
            tgt.q.push(key, req, forced=True)
        self._parked = keep

    def _quarantine_locked(self, ex: _DeviceExecutor, now: float):
        """Trip the breaker: mark ``ex`` quarantined (no routed
        traffic, no stealing), arm its cooldown, strip its bucket
        homes, and re-home its whole backlog onto healthy executors
        via the absorb path (re-running every deadline/cancel check,
        exactly like a work-steal migration)."""
        ex.health = HEALTH_QUARANTINED
        ex.breaker.trip(now)
        self._breaker_trips += 1
        profiling.counter_inc('serve.breaker_trips')
        self.flight_recorder.record('breaker_trip',
                                    executor=ex.label(),
                                    breaker=ex.breaker.snapshot())
        for key in [k for k, i in self._home.items() if i == ex.idx]:
            del self._home[key]
            self._home_counts[ex.idx] -= 1
        for key, reqs in ex.q.migrate_all().items():
            tgt = self._route_locked(key)
            if self._tracer.enabled:
                dst = 'parked' if tgt is None else tgt.label()
                for r in reqs:
                    if r.handle._trace is not None:
                        r.handle._trace.instant('migrate',
                                                src=ex.label(),
                                                dst=dst,
                                                reason='quarantine')
            if tgt is None:
                self._parked.extend((now, key, r) for r in reqs)
            else:
                self._count_expired_locked(
                    tgt.q.absorb(key, reqs, now))
        self._cv.notify_all()

    def _supervise_loop(self):
        """The supervisor thread: every tick it pumps parked retries,
        checks each executor for a dead dispatcher thread (respawn +
        quarantine + retry its in-flight batch), a dispatch past the
        hang watchdog (quarantine + retry elsewhere; the straggler's
        eventual completion is token-stale), and a quarantined
        executor whose cooldown elapsed (launch a canary probe)."""
        while True:
            with self._cv:
                if self._stop_supervisor:
                    return
                now = time.monotonic()
                self._pump_parked_locked(now)
                self._expire_sessions_locked(now)
                for ex in self._executors:
                    if not ex.thread.is_alive() and not self._closing:
                        self._on_executor_death_locked(ex, now)
                    elif ex.dispatch_deadline is not None \
                            and now > ex.dispatch_deadline:
                        self._on_executor_hang_locked(ex, now)
                    if ex.health == HEALTH_QUARANTINED \
                            and ex.canary_thread is None \
                            and not self._closing \
                            and ex.breaker.ready_to_probe(now):
                        self._start_canary_locked(ex)
                self._cv.wait(self._supervise_interval_s)

    def _on_executor_death_locked(self, ex: _DeviceExecutor,
                                  now: float):
        """The dispatcher thread died (a non-Exception throwable out
        of a dispatch, or a bug): recover its in-flight batch into the
        retry path, quarantine the executor, and respawn a fresh
        dispatcher so the pool never shrinks permanently."""
        self._executor_deaths += 1
        ex.deaths += 1
        profiling.counter_inc('serve.executor_deaths')
        self.flight_recorder.record(
            'executor_death', executor=ex.label(),
            inflight=0 if ex.inflight is None else len(ex.inflight[1]))
        inflight, ex.inflight = ex.inflight, None
        ex.busy = False
        ex.dispatch_deadline = None
        self._quarantine_locked(ex, now)
        if inflight is not None:
            key, batch = inflight
            self._retry_batch_locked(key, batch, ExecutorLostError(
                f'dispatcher thread for executor {ex.label()} died '
                f'mid-dispatch'), now)
        ex.respawns += 1
        ex.spawn_thread(self)
        ex.thread.start()
        self.flight_recorder.record('respawn', executor=ex.label(),
                                    respawns=ex.respawns)
        self._dump_flight_auto()
        self._cv.notify_all()

    def _on_executor_hang_locked(self, ex: _DeviceExecutor,
                                 now: float):
        """The current dispatch blew past ``hang_timeout_s``: retry
        its batch on healthy executors NOW (fresh attempt tokens make
        the hung dispatch's eventual completion a no-op) and
        quarantine the executor — the canary decides when it is
        trustworthy again."""
        self._hangs += 1
        ex.hangs += 1
        profiling.counter_inc('serve.hangs')
        self.flight_recorder.record(
            'hang', executor=ex.label(),
            hang_timeout_s=self._hang_timeout_s,
            inflight=0 if ex.inflight is None else len(ex.inflight[1]))
        inflight, ex.inflight = ex.inflight, None
        ex.dispatch_deadline = None
        self._quarantine_locked(ex, now)
        if inflight is not None:
            key, batch = inflight
            self._retry_batch_locked(key, batch, ExecutorLostError(
                f'dispatch on executor {ex.label()} exceeded '
                f'hang_timeout_s={self._hang_timeout_s}'), now)
        self._dump_flight_auto()
        self._cv.notify_all()

    def _start_canary_locked(self, ex: _DeviceExecutor):
        """Half-open probe: run one tiny known program on the
        quarantined executor in a short-lived thread (through
        ``_run_batch``, so fault injection exercises this path too)."""
        ex.health = HEALTH_PROBING
        ex.canary_thread = threading.Thread(
            target=self._canary_probe, args=(ex,),
            name=f'{CANARY_THREAD_PREFIX}-{self.name}-d{ex.idx}',
            daemon=True)
        ex.canary_thread.start()

    def _canary_work(self):
        """The canary workload: a tiny branch-free single-core
        program (its own 1-program bucket, so a canary compile never
        perturbs serving buckets), built once and reused."""
        if self._canary_mp is None:
            core = [isa.pulse_cmd(amp_word=1000, cfg_word=0,
                                  env_word=3, cmd_time=10),
                    isa.done_cmd()]
            self._canary_mp = machine_program_from_cmds([core])
        mp = self._canary_mp
        ncfg, _ = _normalize_cfg(None, isa.shape_bucket(mp.n_instr))
        key = bucket_key(mp, ncfg)
        meas = np.zeros((1, mp.n_cores, ncfg.max_meas), np.int32)
        req = Request(mp=mp, meas_bits=meas, init_regs=None, cfg=ncfg,
                      strict=False, n_shots=1, priority=0,
                      deadline=None, seq=-1)
        return key, [req], ncfg

    def _canary_probe(self, ex: _DeviceExecutor):
        """Runs on the canary thread.  Success needs a clean run AND
        bit-identity with the first successful canary anywhere in the
        pool — a device that computes WRONG bits stays quarantined
        just like one that crashes.  Success re-admits the executor
        (health live, breaker reset, parked work pumped); failure
        re-arms the quarantine with an escalated cooldown."""
        ok = False
        try:
            key, batch, ncfg = self._canary_work()
            out = self._run_batch(ex, key, batch, ncfg)[0]
            ref = {k: np.asarray(v) for k, v in out.items()}
            clean = not np.asarray(ref.get('fault', 0)).any()
            with self._cv:
                if self._canary_ref is None:
                    self._canary_ref = ref
                    ok = clean
                else:
                    ok = clean and set(ref) == set(self._canary_ref) \
                        and all(np.array_equal(ref[k],
                                               self._canary_ref[k])
                                for k in self._canary_ref)
        except BaseException:   # noqa: BLE001 - injected faults included
            ok = False
        now = time.monotonic()
        with self._cv:
            ex.canary_thread = None
            self.flight_recorder.record('canary', executor=ex.label(),
                                        ok=ok)
            if ok:
                ex.canary_ok += 1
                self._canary_ok += 1
                profiling.counter_inc('serve.canary.ok')
                ex.health = HEALTH_LIVE
                ex.breaker.readmit()
                self._readmissions += 1
                profiling.counter_inc('serve.readmissions')
                self.flight_recorder.record('readmission',
                                            executor=ex.label())
                self._pump_parked_locked(now)
            else:
                ex.canary_fail += 1
                self._canary_fail += 1
                profiling.counter_inc('serve.canary.fail')
                ex.health = HEALTH_QUARANTINED
                ex.breaker.trip(now)
            self._cv.notify_all()

    # -- background scrubber (docs/ROBUSTNESS.md "Integrity") ------------

    def _scrub_loop(self):
        """The scrubber thread: every ``scrub_interval_s`` it replays
        the golden canary program on each idle live executor and
        bit-compares against the pool-wide canary reference.  A
        device that has started corrupting fails ``breaker_threshold``
        consecutive scrubs and goes through the standard
        quarantine -> canary re-admission lifecycle — benched by the
        same machinery that benches a crashing one, without waiting
        for tenant traffic to trip an audit."""
        while True:
            with self._cv:
                if self._closing:
                    return
                self._cv.wait(self._scrub_interval_s)
                if self._closing:
                    return
                idle = [ex for ex in self._executors
                        if ex.health == HEALTH_LIVE and not ex.busy]
            for ex in idle:
                self._scrub_one(ex)

    def _scrub_one(self, ex: _DeviceExecutor):
        with self._cv:
            if self._closing or ex.health != HEALTH_LIVE or ex.busy:
                return
            self._scrubber_runs += 1
        profiling.counter_inc('integrity.scrubber_runs')
        ok = False
        try:
            key, batch, ncfg = self._canary_work()
            # through _run_batch, so chaos injection (including
            # 'corrupt') exercises the scrubber exactly like traffic
            out = self._run_batch(ex, key, batch, ncfg)[0]
            ref = {k: np.asarray(v) for k, v in out.items()}
            with self._cv:
                if self._canary_ref is None:
                    clean = not np.asarray(ref.get('fault', 0)).any()
                    if clean:
                        self._canary_ref = ref
                    ok = clean
                else:
                    ok = not diff_stats(ref, self._canary_ref)
        except BaseException:   # noqa: BLE001 - injected faults included
            ok = False
        now = time.monotonic()
        with self._cv:
            if ok:
                ex.scrub_fails = 0
                return
            ex.scrub_fails += 1
            self._scrubber_fail += 1
            self.flight_recorder.record('scrubber_fail',
                                        executor=ex.label(),
                                        consecutive=ex.scrub_fails)
            if ex.scrub_fails >= self._breaker_threshold \
                    and ex.health == HEALTH_LIVE and self._supervision:
                self._integrity_quarantines += 1
                profiling.counter_inc('integrity.quarantines')
                ex.scrub_fails = 0
                self._quarantine_locked(ex, now)

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self, ex: _DeviceExecutor):
        while True:
            with self._cv:
                while True:
                    now = time.monotonic()
                    ex.last_beat = now       # supervisor heartbeat
                    flush = self._closing and self._drain
                    self._pump_parked_locked(now, flush=flush)
                    # a quarantined/probing executor receives no routed
                    # traffic and may not pop or steal — except during a
                    # draining shutdown, when everyone helps flush
                    if ex.health == HEALTH_LIVE or flush:
                        key, batch, expired = ex.q.pop_batch(
                            now, flush=flush)
                        self._count_expired_locked(expired)
                        if key is None and self._stealing:
                            if self._try_steal_locked(ex, now,
                                                      flush=flush):
                                continue     # absorbed work: pop it now
                        if key is not None:
                            ex.busy = True
                            ex.inflight = (key, batch)
                            self._trace_claimed(ex, key, batch, now)
                            if self._hang_timeout_s is not None:
                                ex.dispatch_deadline = \
                                    now + self._hang_timeout_s
                            # wake idle peers: the remaining ripe
                            # buckets just became stealable
                            self._cv.notify_all()
                            break
                    if self._closing and (not self._drain
                                          or self._depth_locked() == 0):
                        return
                    if ex.health != HEALTH_LIVE:
                        self._cv.wait(0.25)
                        continue
                    timeout = self._wait_timeout_locked(ex, now)
                    if timeout is None:
                        self._cv.wait()
                    elif timeout > 0:
                        self._cv.wait(timeout)
                    else:
                        # something is ripe somewhere but not claimable
                        # by this executor yet: bounded re-check
                        self._cv.wait(0.002)
            done = False
            try:
                self._execute(ex, key, batch)
                done = True
            finally:
                with self._cv:
                    ex.busy = False
                    ex.dispatch_deadline = None
                    if done:
                        ex.inflight = None
                    # else the thread is dying on a non-Exception
                    # throwable mid-dispatch: leave inflight for the
                    # supervisor's dead-thread recovery to retry
                    self._cv.notify_all()

    def _wait_timeout_locked(self, ex: _DeviceExecutor,
                             now: float) -> float:
        """Condition-wait horizon: this executor's next queue event,
        or — with stealing on — any peer's (a peer's bucket ripening
        may become this executor's work)."""
        t = ex.q.next_event(now)
        if self._stealing:
            for v in self._executors:
                if v is ex:
                    continue
                tv = v.q.next_event(now)
                if tv is not None:
                    t = tv if t is None else min(t, tv)
        if self._parked:
            # a parked retry becoming eligible is a queue event too —
            # without this, a dispatcher could sleep unbounded while a
            # retry waits out its backoff (supervision may be off)
            tp = max(min(e[0] for e in self._parked) - now, 0.0)
            t = tp if t is None else min(t, tp)
        return t

    # -- tracing emission (docs/OBSERVABILITY.md) ------------------------

    def _trace_claimed(self, ex: _DeviceExecutor, key, batch,
                       now: float) -> None:
        """Close the queued + coalesce-ripen spans of every traced
        batch member at the moment the dispatcher claims the batch
        (called under the cv, right where pop_batch claimed)."""
        if not self._tracer.enabled:
            return
        oldest = min(r.submit_t for r in batch)
        for r in batch:
            ctx = r.handle._trace
            if ctx is None:
                continue
            # a retried request re-queues mid-flight: clamp the queued
            # span to start no earlier than its previous claim so the
            # per-attempt chain stays ordered
            t_q = r.submit_t if ctx.last_claim is None \
                else max(r.submit_t, ctx.last_claim)
            ctx.span('queued', t_q, now, bucket=key.label(),
                     executor=ex.label(),
                     attempt=r.handle.retries + 1)
            ctx.span('coalesce.ripen', max(oldest, t_q), now,
                     occupancy=len(batch))
            ctx.last_claim = now

    def _trace_dispatch(self, batch, ex: _DeviceExecutor, label: str,
                        klass: str, engine: str,
                        occupancy: int) -> None:
        """Record the dispatch span (claim → simulate entry) with the
        device, the bound-bucket identity, and the compile
        classification (cold / warm / aot)."""
        now = time.monotonic()
        for r in batch:
            ctx = r.handle._trace
            if ctx is None:
                continue
            t0 = now if ctx.last_claim is None else ctx.last_claim
            ctx.span('dispatch', t0, now, device=ex.label(),
                     bucket=label, classification=klass,
                     engine=engine, occupancy=occupancy)

    def _execute(self, ex: _DeviceExecutor, key, batch):
        cfg = key.cfg
        t0 = time.monotonic()
        try:
            results = self._run_batch(ex, key, batch, cfg)
        except Exception as exc:      # noqa: BLE001 - fail the batch, live on
            self._on_batch_failure(ex, key, batch, exc)
            return
        # streaming chunks are excluded from the differential audit:
        # its re-execution path is single-round simulate_batch, which
        # cannot consume the [R, B, C, M] rounds layout
        if self._audit_every and batch[0].rounds is None:
            with self._cv:
                self._audit_tick += 1
                do_audit = self._audit_tick % self._audit_every == 0
            if do_audit:
                bad = self._audit_batch(ex, key, batch, cfg, results)
                if bad is not None:
                    # strict policy: the tainted bits never reach a
                    # handle — the batch takes the infrastructure
                    # retry path (fresh execution re-derives the
                    # truth) and the breaker hears about it
                    self._on_batch_failure(ex, key, batch, bad)
                    return
        t_run = time.monotonic()
        completed = failed = served_rounds = 0
        served = []     # token-valid resolutions: the billable set
        for req, res in zip(batch, results):
            # every completion presents the attempt token: if this
            # dispatch was declared hung and the request retried
            # elsewhere, the token is stale and the write is a no-op
            if req.strict:
                counts = np.asarray(fault_shot_counts(res['fault']))
                if counts.any():
                    if req.handle._fail(FaultError(counts),
                                        token=req.claim_token):
                        failed += 1
                        served.append(req)
                    continue
            if req.handle._fulfill(res, token=req.claim_token):
                completed += 1
                served.append(req)
                if req.rounds is not None:
                    served_rounds += req.rounds
        now = time.monotonic()
        if self._tracer.enabled:
            for req in batch:
                ctx = req.handle._trace
                if ctx is not None:
                    ctx.span('execute', t0, t_run, device=ex.label(),
                             bucket=key.label())
                    ctx.span('demux', t_run, now)
        with self._cv:
            self._dispatches += 1
            self._programs_dispatched += len(batch)
            self._occupancy[len(batch)] += 1
            ex.dispatches += 1
            ex.programs_dispatched += len(batch)
            ex.occupancy[len(batch)] += 1
            self._completed += completed
            self._failed += failed
            if served_rounds:
                self._stream_rounds_served += served_rounds
            # round-deadline misses at the scan-chunk boundary: a
            # chunk that completed PAST its deadline still served its
            # bits, but every round it carried missed its budget
            late = sum(req.rounds for req in batch
                       if req.rounds is not None
                       and req.deadline is not None
                       and now > req.deadline)
            if late:
                self._stream_round_misses += late
            for req in batch:
                if req.sid is not None and req.sid in self._sessions:
                    self._sessions[req.sid] = now
            ex.breaker.record_success()
            per_prog = (now - t0) / len(batch)
            self._ewma_prog_s = per_prog if self._ewma_prog_s is None \
                else 0.25 * per_prog + 0.75 * self._ewma_prog_s
            for req in batch:
                lat_ms = (now - req.submit_t) * 1e3
                self._latency_h.observe(lat_ms)
                profiling.registry().observe('serve.latency_ms',
                                             lat_ms)
            # usage metering, exactly-once by construction: only the
            # token-valid resolutions above are billed, so a chaos
            # kill + retry can neither lose nor double-count a
            # request's usage (a stale straggler's write was a no-op
            # and never reached `served`)
            per_prog_ms = per_prog * 1e3
            for req in served:
                ts = self._tenant_locked(req.tenant)
                sh = req.n_shots * (req.rounds or 1)
                ts['shots'] += sh
                ts['device_ms'] += per_prog_ms
                profiling.counter_inc(f'tenant.{req.tenant}.shots', sh)
                profiling.counter_inc(f'tenant.{req.tenant}.device_ms',
                                      int(round(per_prog_ms)))
        profiling.counter_inc('serve.dispatches')
        profiling.counter_inc('serve.programs_dispatched', len(batch))
        profiling.counter_inc('serve.batch_ms',
                              int((now - t0) * 1e3))
        if served_rounds:
            profiling.counter_inc('serve.stream.rounds_served',
                                  served_rounds)
        if late:
            profiling.counter_inc('serve.stream.round_deadline_misses',
                                  late)

    def _on_batch_failure(self, ex: _DeviceExecutor, key, batch, exc):
        """A batch raised out of ``_run_batch``.  Program-class errors
        (:func:`is_infrastructure_error` False — validation, bad
        arguments: they reproduce identically anywhere) propagate to
        every handle immediately; infrastructure-class errors feed the
        executor's circuit breaker and send the batch through the
        bounded-retry path."""
        profiling.counter_inc('serve.batch_failures')
        infra = is_infrastructure_error(exc)
        self.flight_recorder.record('batch_failure',
                                    executor=ex.label(),
                                    error=type(exc).__name__,
                                    infra=infra, n=len(batch))
        if self._tracer.enabled:
            for req in batch:
                ctx = req.handle._trace
                if ctx is not None:
                    ctx.instant('batch_error',
                                error=type(exc).__name__,
                                executor=ex.label())
        if not infra:
            failed = 0
            for req in batch:
                if req.handle._fail(exc, token=req.claim_token):
                    failed += 1
            with self._cv:
                self._failed += failed
            return
        now = time.monotonic()
        with self._cv:
            tripped = ex.breaker.record_failure()
            if tripped and ex.health == HEALTH_LIVE \
                    and self._supervision:
                if isinstance(exc, IntegrityError):
                    self._integrity_quarantines += 1
                    profiling.counter_inc('integrity.quarantines')
                self._quarantine_locked(ex, now)
            self._retry_batch_locked(key, batch, exc, now)
            self._cv.notify_all()

    def _retry_batch_locked(self, key, batch, exc, now: float):
        """Send a batch that died on executor infrastructure through
        the :class:`RetryPolicy`: each request re-queues (invalidating
        its old attempt token) and parks until its backoff elapses;
        one out of budget fails with the ORIGINAL infrastructure error
        it hit.  Requests already resolved (cancel / deadline / a
        racing completion) are skipped by the token guard."""
        policy = self._retry_policy
        for req in batch:
            if req.last_error is None:
                req.last_error = exc
            if req.handle.retries + 1 >= policy.max_attempts:
                if req.handle._fail(req.last_error,
                                    token=req.claim_token):
                    self._failed += 1
                    self._retry_exhausted += 1
                    profiling.counter_inc('serve.retry_exhausted')
                    self.flight_recorder.record(
                        'retry_exhausted', req=req.seq,
                        attempts=req.handle.retries + 1,
                        error=type(req.last_error).__name__)
            elif req.handle._requeue(req.claim_token):
                self._retries += 1
                profiling.counter_inc('serve.retries')
                delay = policy.delay_s(req.handle.retries - 1)
                self.flight_recorder.record(
                    'retry', req=req.seq, attempt=req.handle.retries,
                    delay_ms=round(delay * 1e3, 3),
                    error=type(exc).__name__)
                ctx = req.handle._trace
                if ctx is not None:
                    ctx.instant('retry', attempt=req.handle.retries,
                                backoff_ms=round(delay * 1e3, 3),
                                error=type(exc).__name__)
                    ctx.instant('park', reason='retry-backoff')
                self._parked.append((now + delay, key, req))

    # -- differential audit (docs/ROBUSTNESS.md "Integrity") -------------

    def _audit_engine(self, mp, cfg, served: str) -> str:
        """The audit rung: the first engine of the CPU-safe ladder
        subset that is not the one that served and accepts this
        program — a differential re-execution is only evidence when
        the second opinion goes through an independent code path."""
        for eng in ('block', 'straightline', 'generic'):
            if eng == served:
                continue
            try:
                resolve_engine(mp, replace(cfg, engine=eng))
                return eng
            except ValueError:
                continue
        return 'generic'

    def _audit_batch(self, ex: _DeviceExecutor, key, batch, cfg,
                     results):
        """Re-execute every request of a completed batch on a
        DIFFERENT engine (and a different live device when the pool
        has one) and bit-compare per stat, fault words included.

        Timing-dependent fault codes (budget exhaustion, deadlock,
        starvation) legitimately differ across engines, so a
        cross-engine disagreement alone is not corruption: it
        escalates to a confirm re-run under the exact served
        configuration, and only a confirmed mismatch counts — a real
        bit flip disagrees with ANY correct re-execution, so
        detection survives the escalation while legitimate engine
        divergence never cries wolf.  Audit-internal failures are
        inconclusive and never punish the batch.

        Returns the :class:`IntegrityError` to fail the batch with
        under ``audit_mode='strict'``, else None (flag mode records
        the violation and lets delivery proceed)."""
        with self._cv:
            self._audits += 1
            alts = [v.device for v in self._executors
                    if v is not ex and v.health == HEALTH_LIVE]
        profiling.counter_inc('integrity.audits')
        alt_dev = alts[0] if alts else ex.device
        singleton = len(batch) == 1 and self.singleton_engine is not None
        bad = []
        for req, res in zip(batch, results):
            try:
                want = {k: np.asarray(v) for k, v in res.items()}
                if singleton:
                    scfg = replace(cfg, engine=self.singleton_engine)
                    served = resolve_engine(req.mp, scfg)
                else:
                    # the multi path is the generic engine; the solo
                    # generic run is its documented bit-identical
                    # equivalent (padding is inert, demux trims it)
                    scfg = replace(cfg, engine='generic')
                    served = 'generic'
                alt = self._audit_engine(req.mp, cfg, served)
                got = jax.tree.map(np.asarray, simulate_batch(
                    req.mp, req.meas_bits, req.init_regs,
                    cfg=replace(cfg, engine=alt),
                    jax_device=alt_dev))
                keys = diff_stats(got, want)
                if keys and alt != served:
                    got = jax.tree.map(np.asarray, simulate_batch(
                        req.mp, req.meas_bits, req.init_regs,
                        cfg=scfg, jax_device=alt_dev))
                    keys = diff_stats(got, want)
                if keys:
                    bad.append((req.seq, keys))
            except Exception:   # noqa: BLE001 - inconclusive audit
                continue
        with self._cv:
            self._audit_mismatches += len(bad)
            was_bad = ex.integrity_bad
            ex.integrity_bad = bool(bad)
        if not bad:
            return None
        profiling.counter_inc('integrity.mismatches', len(bad))
        if not was_bad:
            # edge-triggered: a persistently-corrupting executor logs
            # one violation event, not one per audited batch
            self.flight_recorder.record(
                'integrity_violation', executor=ex.label(),
                mode=self._audit_mode, n=len(bad),
                stats=sorted({k for _, keys in bad for k in keys}))
        if self._audit_mode != 'strict':
            return None
        seqs = [seq for seq, _ in bad]
        return IntegrityError(
            f'audit mismatch on executor {ex.label()}: requests '
            f'{seqs} disagree with differential re-execution '
            f'(silent data corruption)')

    def _run_batch(self, ex: _DeviceExecutor, key, batch, cfg):
        """Execute one coalesced batch on ``ex``'s device; returns
        per-request stats dicts in batch order (host numpy, padding
        trimmed)."""
        if batch[0].rounds is not None:
            return self._run_stream_batch(ex, key, batch)
        if len(batch) == 1 and self.singleton_engine is not None:
            req = batch[0]
            scfg = replace(cfg, engine=self.singleton_engine)
            eng = resolve_engine(req.mp, scfg)
            self._count_engine_locked(ex, eng)
            cold = self._classify_compile(
                ex, key, ('solo', eng, req.n_shots,
                          req.init_regs is None))
            if self._tracer.enabled:
                self._trace_dispatch(batch, ex, key.label(),
                                     'cold' if cold else 'warm', eng,
                                     1)
            t0 = time.monotonic()
            out = simulate_batch(req.mp, req.meas_bits, req.init_regs,
                                 cfg=scfg, jax_device=ex.device)
            res = [jax.tree.map(np.asarray, out)]
            self._record_bucket_ms(key, cold, time.monotonic() - t0)
            return res
        B = max(r.n_shots for r in batch)
        P = _pow2(len(batch)) if self.pad_programs else len(batch)
        pad = P - len(batch)
        # program-count padding replicates the LAST request: its lanes
        # are deterministic copies, and demux only reads the first
        # len(batch) program slots — inert, but it keeps odd-sized
        # remainders and stolen batches on the pow2-shaped executables
        meas = np.stack(
            [_pad_shots(r.meas_bits, B) for r in batch]
            + [_pad_shots(batch[-1].meas_bits, B)] * pad)
        if any(r.init_regs is not None for r in batch):
            rows = [_pad_shots(r.init_regs, B) if r.init_regs is not None
                    else np.zeros((B, r.mp.n_cores, isa.N_REGS), np.int32)
                    for r in batch]
            init = np.stack(rows + [rows[-1]] * pad)
        else:
            init = None
        mmp = stack_machine_programs(
            [r.mp for r in batch] + [batch[-1].mp] * pad,
            pad_to=key_bucket(batch))
        self._count_engine_locked(ex, 'generic')
        cold = self._classify_compile(ex, key,
                                      ('multi', P, B, init is None))
        # the catalog stores the EXACT executable identity: the
        # stacked batch's trait union, not any one member's traits
        bspec = replace(key, traits=program_traits(mmp)).bind(
            n_programs=P, n_shots=B, has_init_regs=init is not None)
        self._record_catalog(bspec)
        if self._tracer.enabled:
            # three-way dispatch classification: a precompiled AOT
            # executable beats the cold/warm jit split (the lookup the
            # interpreter itself makes on dispatch)
            klass = 'aot' if aot_batch_cached(bspec, ex.device) \
                else ('cold' if cold else 'warm')
            self._trace_dispatch(batch, ex, bspec.label(), klass,
                                 'generic', P)
        t0 = time.monotonic()
        out = simulate_multi_batch(mmp, meas, init, cfg=cfg,
                                   jax_device=ex.device)
        # np.asarray blocks on the device result, so the timed window
        # covers trace+compile+execute — the cold/warm latency split
        # stats() turns into a compile-cost estimate per bucket
        host = jax.tree.map(np.asarray, out)
        self._record_bucket_ms(key, cold, time.monotonic() - t0)
        return [demux_multi_batch(host, i, n_shots=r.n_shots)
                for i, r in enumerate(batch)]

    def _run_stream_batch(self, ex: _DeviceExecutor, key, batch):
        """Execute streaming round chunks: one
        :func:`~..sim.interpreter.simulate_rounds` scan per request
        (chunks of one session coalescing under their shared sticky
        key still execute sequentially — each carries its own round
        count, and the scan IS the batching).  The chunk cfg rides the
        REQUEST (``rounds`` rebound per chunk), not the routing key."""
        results = []
        for req in batch:
            rcfg = req.cfg
            eng = resolve_engine(req.mp, rcfg)
            self._count_engine_locked(ex, eng)
            cold = self._classify_compile(
                ex, key, ('stream', eng, req.rounds, req.n_shots,
                          req.init_regs is None, req.decode))
            if self._tracer.enabled:
                self._trace_dispatch([req], ex, key.label(),
                                     'cold' if cold else 'warm', eng,
                                     1)
            t0 = time.monotonic()
            out = simulate_rounds(req.mp, req.meas_bits, req.init_regs,
                                  cfg=rcfg, jax_device=ex.device,
                                  decode=req.decode)
            results.append(jax.tree.map(np.asarray, out))
            self._record_bucket_ms(key, cold, time.monotonic() - t0)
        return results

    def _count_engine_locked(self, ex: _DeviceExecutor, eng: str):
        """Record which ladder rung a dispatch actually ran on (the
        multi path is generic by construction; the singleton path
        resolves 'auto' the same way ``simulate_batch`` will)."""
        with self._cv:
            self._engine_dispatches[eng] += 1
            ex.engine_dispatches[eng] += 1
        profiling.counter_inc(f'serve.engine.{eng}')

    def _classify_compile(self, ex: _DeviceExecutor, key,
                          shape_sig: tuple) -> bool:
        """Host-side cold/warm jit classification: the first dispatch
        of a (bucket, shape signature) on a device is a compile, every
        repeat is a warm cache hit — the same shapes the jit cache
        itself keys on, tracked per executor because cache entries are
        per device.  (An estimate: a process-shared persistent compile
        cache can make a "cold" entry cheap, and content-keyed
        singleton engines can recompile under an unchanged signature.)
        Groundwork for the ROADMAP AOT-warmup item via :meth:`warmup`.
        """
        sig = (key, shape_sig)
        with self._cv:
            cold = sig not in ex.seen
            if cold:
                ex.seen.add(sig)
                ex.cold_compiles += 1
            else:
                ex.warm_hits += 1
            per = self._bucket_label_entry_locked(key)
            per['cold' if cold else 'warm'] += 1
        profiling.counter_inc(
            'serve.compile.cold' if cold else 'serve.compile.warm')
        return cold

    def _bucket_label_entry_locked(self, key) -> dict:
        return self._bucket_compiles.setdefault(
            _bucket_label(key),
            {'cold': 0, 'warm': 0, 'cold_s': 0.0, 'warm_s': 0.0,
             'cold_timed': 0, 'warm_timed': 0})

    def _record_bucket_ms(self, key, cold: bool, dt_s: float) -> None:
        """Accrue one timed dispatch into the bucket's cold/warm
        latency split (warmup classifications are untimed, so counts
        and timed-sample counts are tracked separately)."""
        with self._cv:
            per = self._bucket_label_entry_locked(key)
            which = 'cold' if cold else 'warm'
            per[which + '_s'] += dt_s
            per[which + '_timed'] += 1

    def _record_catalog(self, spec: BucketSpec) -> None:
        """Persist a dispatched bucket into the learned catalog (no-op
        without one; deduped in memory so steady-state dispatch never
        touches the filesystem)."""
        if self._catalog is None:
            return
        with self._cv:
            if spec.identity() in self._catalog_seen:
                return
            self._catalog_seen.add(spec.identity())
        self._catalog.record(spec)

    # -- warmup ----------------------------------------------------------

    def bucket_spec(self, mp, *, shots: int = 1, n_programs: int = None,
                    cfg: InterpreterConfig = None) -> BucketSpec:
        """The BOUND :class:`BucketSpec` a ``(mp, cfg)`` submission
        would dispatch into at ``n_programs`` batch occupancy (default
        ``max_batch_programs``; pow2-padded exactly like live
        dispatch) and ``shots`` — the value :meth:`warmup` compiles
        and the catalog stores."""
        n_programs = n_programs if n_programs is not None \
            else self.max_batch_programs
        n_programs = max(1, min(n_programs, self.max_batch_programs))
        base = cfg if cfg is not None else self._default_cfg
        ncfg, _ = _normalize_cfg(base, isa.shape_bucket(mp.n_instr))
        P = _pow2(n_programs) if self.pad_programs else n_programs
        return bucket_key(mp, ncfg).bind(n_programs=P,
                                         n_shots=int(shots))

    def warmup(self, specs=None, *, shots: int = 1,
               n_programs: int = None,
               cfg: InterpreterConfig = None) -> list:
        """AOT-precompile serving executables on EVERY device executor
        (``sim.interpreter.aot_compile_batch`` — ``lower().compile()``
        against abstract shapes, no real program dispatched), so the
        first real request in a warmed bucket never eats the XLA
        compile inside its latency budget.

        ``specs`` is a bound :class:`BucketSpec`, an iterable of them,
        or (backward compatible) a machine program — then
        ``shots``/``n_programs``/``cfg`` describe the representative
        batch exactly as before and :meth:`bucket_spec` derives the
        spec.  An executable is shape-exact — (programs, shots, cores,
        instruction bucket, cfg) — so warm coverage needs the
        occupancies traffic will actually dispatch (the benches warm
        every power of two up to ``max_batch_programs``).

        Counted in ``stats()['compile']`` / ``serve.compile.*`` like a
        dispatch (warmup compiles classify cold; the first real
        request then classifies warm).  Returns one ``{'device',
        'spec', 'cold', 'compile_ms'}`` dict per (spec, executor) —
        ``compile_ms`` 0.0 when the executable was already cached.

        Covers the coalesced multi-program path; a ``singleton_engine``
        fallback dispatch is content-keyed and cannot be AOT-compiled
        from a shape alone."""
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
        if specs is None:
            raise ValueError('warmup needs a bound BucketSpec, an '
                             'iterable of them, or a machine program')
        if hasattr(specs, 'n_instr'):      # a MachineProgram (legacy)
            specs = [self.bucket_spec(specs, shots=shots,
                                      n_programs=n_programs, cfg=cfg)]
        elif isinstance(specs, BucketSpec):
            specs = [specs]
        else:
            specs = list(specs)
        report = []
        for spec in specs:
            if not spec.bound:
                raise ValueError(
                    f'warmup needs BOUND specs (BucketSpec.bind / '
                    f'bucket_spec); got template {spec.label()!r}')
            for ex in self._executors:
                dt = aot_compile_batch(spec, ex.device)
                cold = self._classify_compile(ex, spec.template(),
                                              spec.shape_sig())
                with self._cv:
                    self._warmups += 1
                    if dt > 0:
                        self._warmup_aot += 1
                profiling.counter_inc('serve.warmups')
                report.append({'device': ex.label(),
                               'spec': spec.label(), 'cold': cold,
                               'compile_ms': dt * 1e3})
        return report

    def _warmup_replay(self, specs: list) -> None:
        """Background catalog replay (the ``dproc-serve-warmup-*``
        thread): AOT-compile every recorded spec on every executor.
        Never blocks admission — dispatch takes the lazy path for any
        bucket whose replay has not landed yet — and a bad catalog
        entry is skipped, never surfaced to a request."""
        for spec in specs:
            compiled_any = False
            for ex in self._executors:
                with self._cv:
                    if self._closing:
                        self._warmup_pending = 0
                        return
                try:
                    dt = aot_compile_batch(spec, ex.device)
                except Exception:   # noqa: BLE001 - tolerate bad entries
                    with self._cv:
                        self._warmup_pending -= 1
                    continue
                # mark the (bucket, shape) seen so the first real
                # request classifies warm — which it IS, it will hit
                # the precompiled executable
                self._classify_compile(ex, spec.template(),
                                       spec.shape_sig())
                with self._cv:
                    self._warmup_pending -= 1
                    if dt > 0:
                        self._warmup_aot += 1
                    compiled_any = True
            with self._cv:
                if compiled_any:
                    self._warmup_replayed += 1
                self._cv.notify_all()
        profiling.counter_inc('serve.warmup_replays')

    # -- introspection / lifecycle ---------------------------------------

    def stats(self) -> dict:
        """Snapshot of the service counters: aggregate queue depth,
        batch occupancy histogram, coalescing efficiency (programs per
        dispatch), p50/p99 submit-to-done latency in ms, cold/warm jit
        compile hits per bucket, and a per-device breakdown (queue
        depth, occupancy, steals, compile hits) for the multi-device
        pool."""
        with self._cv:
            lat = np.asarray(self._latency_h.values(), np.float64)
            occ = dict(sorted(self._occupancy.items()))
            # prune resolved stream chunks lazily: stats() is the only
            # reader of rounds-in-flight, so the live list never grows
            # past the outstanding chunk count between snapshots
            self._stream_live = [(h, r) for h, r in self._stream_live
                                 if not h.done()]
            rounds_in_flight = sum(r for _, r in self._stream_live)
            devices = [{
                'device': ex.label(),
                'index': ex.idx,
                'busy': ex.busy,
                'health': ex.health,
                'queue_depth': len(ex.q),
                'dispatches': ex.dispatches,
                'programs_dispatched': ex.programs_dispatched,
                'batch_occupancy': dict(sorted(ex.occupancy.items())),
                'engine_dispatches': dict(sorted(
                    ex.engine_dispatches.items())),
                'steals': ex.steals,
                'stolen_from': ex.stolen_from,
                'cold_compiles': ex.cold_compiles,
                'warm_hits': ex.warm_hits,
                'home_buckets': self._home_counts[ex.idx],
                'breaker_trips': ex.breaker.trips,
                'consecutive_failures': ex.breaker.consecutive,
                'readmissions': ex.breaker.readmissions,
                'hangs': ex.hangs,
                'deaths': ex.deaths,
                'respawns': ex.respawns,
                'canary_ok': ex.canary_ok,
                'canary_fail': ex.canary_fail,
                'integrity_bad': ex.integrity_bad,
            } for ex in self._executors]
            health = collections.Counter(
                ex.health for ex in self._executors)
            est_s = self._est_wait_s_locked()
            snap = {
                'queue_depth': self._depth_locked(),
                'submitted': self._submitted,
                'completed': self._completed,
                'failed': self._failed,
                'cancelled': self._cancelled + sum(
                    ex.q.dropped_cancelled for ex in self._executors),
                'expired': self._expired,
                'rejected': self._rejected,
                'dispatches': self._dispatches,
                'programs_dispatched': self._programs_dispatched,
                'batch_occupancy': occ,
                'engine_dispatches': dict(sorted(
                    self._engine_dispatches.items())),
                'coalesce_efficiency': (
                    self._programs_dispatched / self._dispatches
                    if self._dispatches else 0.0),
                'n_devices': len(self._executors),
                'work_stealing': self._stealing,
                'steals': self._steals,
                'warmups': self._warmups,
                'warmup': {
                    'aot_compiled': self._warmup_aot,
                    'replayed': self._warmup_replayed,
                    'in_progress': self._warmup_pending,
                },
                'supervision': self._supervision,
                'health': {state: health.get(state, 0)
                           for state in (HEALTH_LIVE,
                                         HEALTH_QUARANTINED,
                                         HEALTH_PROBING)},
                'parked': len(self._parked),
                'retries': self._retries,
                'retry_exhausted': self._retry_exhausted,
                'shed': self._shed,
                'overload_rejected': self._overload_rejected,
                'breaker_trips': self._breaker_trips,
                'readmissions': self._readmissions,
                'executor_deaths': self._executor_deaths,
                'hangs': self._hangs,
                'canary': {'ok': self._canary_ok,
                           'fail': self._canary_fail},
                'integrity': {
                    'audit_sample': self._audit_sample,
                    'audit_mode': self._audit_mode,
                    'audits': self._audits,
                    'mismatches': self._audit_mismatches,
                    'scrubber_runs': self._scrubber_runs,
                    'scrubber_fail': self._scrubber_fail,
                    'quarantines': self._integrity_quarantines,
                },
                'streaming': {
                    'open_sessions': len(self._sessions),
                    'rounds_in_flight': rounds_in_flight,
                    'rounds_submitted': self._stream_rounds_submitted,
                    'rounds_served': self._stream_rounds_served,
                    'round_deadline_misses': self._stream_round_misses,
                    'sessions_opened': self._stream_sessions_opened,
                    'sessions_expired': self._stream_sessions_expired,
                },
                # calibration traffic (docs/SERVING.md "Calibration
                # sessions"): loop steps ride submit_source, so shots/
                # compiles are already under the ordinary counters —
                # this block is the session-lifecycle view
                'calibration': {
                    'open_sessions': len(self._calib_sessions),
                    'sessions_opened': self._calib_sessions_opened,
                    'steps': self._calib_steps,
                    'converged': self._calib_converged,
                    'diverged': self._calib_diverged,
                },
                'est_wait_ms': None if est_s is None
                else float(est_s * 1e3),
                'compile': {
                    'cold': sum(ex.cold_compiles
                                for ex in self._executors),
                    'warm': sum(ex.warm_hits
                                for ex in self._executors),
                    'per_bucket': {
                        k: _bucket_compile_view(v) for k, v in sorted(
                            self._bucket_compiles.items())},
                },
                'source': {
                    'submitted': self._source_submitted,
                    'pending_compile': len(self._source_handles),
                },
                # per-tenant accounting (docs/SERVING.md "Tenants"):
                # queued/served/shed/quota-rejected plus the billing
                # meters; configured tenants appear even before their
                # first request, unconfigured ones at first sight
                'tenants': {
                    t: dict(ts,
                            weight=self._tenant_weights.get(t, 1.0))
                    for t, ts in sorted(self._tenant_state.items())},
                'devices': devices,
            }
            cache = self._compile_cache
        # program-compile front door counters (hit/miss/singleflight/
        # evict/invalidation + compile-time percentiles); None until the
        # first submit_source/compile_cache touch
        snap['compile_cache'] = None if cache is None else cache.stats()
        if lat.size:
            # the histogram window holds ms already (obs.metrics);
            # same exact-percentile math the old seconds deque used
            snap['latency_p50_ms'] = float(np.percentile(lat, 50))
            snap['latency_p99_ms'] = float(np.percentile(lat, 99))
        else:
            snap['latency_p50_ms'] = snap['latency_p99_ms'] = 0.0
        snap['latency_samples'] = int(lat.size)
        # mirror the load-shaped readings into the registry as gauges
        # (per-service names: a process may run several services)
        reg = profiling.registry()
        reg.set_gauge(f'serve.{self.name}.queue_depth',
                      snap['queue_depth'])
        reg.set_gauge(f'serve.{self.name}.parked', snap['parked'])
        return snap

    # -- observability export (docs/OBSERVABILITY.md) --------------------

    def dump_trace(self, path: str) -> int:
        """Export every retained sampled request trace as Chrome Trace
        Event JSON — loadable in Perfetto / ``chrome://tracing``, and
        summarized per stage by ``cli trace-view``
        (tools/traceview.py).  Returns the event count written."""
        return write_chrome_trace(path, self._tracer.contexts(),
                                  pid=self.name)

    def dump_flight(self, path: str = None) -> str | None:
        """Write the flight-recorder ring to ``path``.  With no path,
        falls back to ``flight_dump_dir`` (or ``$DPROC_FLIGHT_DIR``),
        writing ``flight-<service>.json`` there; returns the written
        path, or None when no destination is configured."""
        if path is None:
            d = self._flight_dump_dir \
                or os.environ.get('DPROC_FLIGHT_DIR')
            if not d:
                return None
            path = os.path.join(d, f'flight-{self.name}.json')
        self.flight_recorder.dump(path)
        return path

    def _dump_flight_auto(self) -> None:
        """Supervisor-detected failure: capture the evidence now,
        best-effort — observability I/O must never take supervision
        down with it."""
        try:
            self.dump_flight()
        except OSError:
            pass

    def shutdown(self, drain: bool = True, timeout: float = None):
        """Stop the service.  ``drain=True`` (default) flushes every
        queued request through dispatch first (all executors keep
        draining — including by stealing — until every queue is empty);
        ``drain=False`` fails queued requests with
        :class:`ShutdownError` (a :class:`CancelledError` subclass;
        in-flight batches still complete).  Joins every dispatcher,
        supervisor and canary thread (up to ``timeout`` seconds EACH),
        then force-fails ANY handle still unresolved — after shutdown
        returns, ``result()`` can never block forever, even when a
        dispatch hung or a dispatcher died (the late straggler's
        completion is discarded as stale).  Idempotent.

        The compile front door participates: ``drain=True`` finishes
        every pending ``submit_source`` compile BEFORE the queues close
        (so its requests flush with the rest); ``drain=False`` cancels
        queued compiles and fails their handles with
        :class:`ShutdownError`."""
        with self._cv:
            pool = self._compile_pool
        if drain and pool is not None:
            # let in-flight source submissions compile and enqueue
            # before the intake closes; their requests then drain below
            pool.shutdown(wait=True)
        with self._cv:
            if not self._closing:
                self._closing = True
                self._drain = drain
                # streaming/calibration sessions close with the
                # service; their outstanding chunks/candidates drain
                # or fail with the rest
                self._sessions.clear()
                self._stream_keys.clear()
                self._calib_sessions.clear()
                if not drain:
                    exc = ShutdownError(
                        f'service {self.name!r} shut down without '
                        f'draining')
                    n = 0
                    for ex in self._executors:
                        n += ex.q.cancel_all(exc)
                    for _, _, req in self._parked:
                        if req.handle._fail(exc):
                            n += 1
                    self._parked = []
                    self._cancelled += n
                    if n:
                        profiling.counter_inc('serve.cancelled', n)
            self._cv.notify_all()
        if not drain and pool is not None:
            # cancel queued compiles; a compile already running hits
            # the closed intake (ServiceClosedError) and fails its own
            # handle.  wait=True keeps the thread-leak probe clean.
            pool.shutdown(wait=True, cancel_futures=True)
            exc = ShutdownError(
                f'service {self.name!r} shut down without draining')
            with self._cv:
                pending_src = list(self._source_handles)
                self._source_handles.clear()
            n = 0
            for h in pending_src:
                if h._fail(exc):
                    n += 1
            if n:
                with self._cv:
                    self._cancelled += n
                profiling.counter_inc('serve.cancelled', n)
        wt = self._warmup_thread
        if wt is not None:
            # the replay loop observes _closing between compiles and
            # exits; join keeps the thread-leak probe clean
            wt.join(timeout)
        for ex in self._executors:
            ex.thread.join(timeout)
        if self._supervisor is not None:
            with self._cv:
                self._stop_supervisor = True
                self._cv.notify_all()
            self._supervisor.join(timeout)
        if self._scrubber is not None:
            # the scrub loop observes _closing (set above, cv
            # notified) both before and after its interval wait
            self._scrubber.join(timeout)
        for ex in self._executors:
            t = ex.canary_thread
            if t is not None:
                t.join(timeout)
        # forced-shutdown guarantee: whatever the joins left behind
        # (a hung dispatch past its join timeout, a dead dispatcher's
        # recovered-but-unserved batch, a parked retry) fails typed NOW
        exc = ShutdownError(
            f'service {self.name!r} shut down with this request '
            f'unresolved')
        with self._cv:
            leftovers = []
            for ex in self._executors:
                if ex.inflight is not None:
                    leftovers.extend(ex.inflight[1])
                    if not ex.thread.is_alive():
                        ex.inflight = None
                leftovers.extend(
                    r for reqs in ex.q.migrate_all().values()
                    for r in reqs)
            leftovers.extend(r for _, _, r in self._parked)
            self._parked = []
            n = 0
            for h in self._source_handles:
                if h._fail(exc):
                    n += 1
            self._source_handles.clear()
            for req in leftovers:
                if req.handle._fail(exc):
                    n += 1
            self._cancelled += n
            if n:
                profiling.counter_inc('serve.cancelled', n)
            self._cv.notify_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.shutdown(drain=exc_info[0] is None)


def key_bucket(batch) -> int:
    """The instruction bucket every member of a coalesced batch pads
    into — identical across the batch by construction (it is part of
    the coalescing key)."""
    return isa.shape_bucket(batch[0].mp.n_instr)
