"""The continuous-batching execution service.

:class:`ExecutionService` is the in-process serving runtime over the
interpreter: any thread calls :meth:`~ExecutionService.submit` with one
compiled :class:`~..decoder.MachineProgram` and gets a
:class:`~.request.RequestHandle` back immediately; dispatcher threads
drain the queues, coalesce compatible requests into shape-bucketed
batches (``batcher.bucket_key``), run each batch through
:func:`~..sim.interpreter.simulate_multi_batch` — hitting the warm jit
cache keyed on the bucket SHAPE — and demux per-request stats back onto
the handles.  The classic continuous-batching contract (vLLM-style,
transplanted from token generation to shot execution):

* latency/throughput dial: a bucket dispatches when it reaches
  ``max_batch_programs`` or its oldest member has waited
  ``max_wait_ms``;
* admission control: a bounded queue (``max_queue``) makes overload a
  synchronous :class:`QueueFullError` at submit, not unbounded growth;
* isolation: ``fault_mode='strict'`` raises
  :class:`~..sim.interpreter.FaultError` on the OFFENDING request's
  handle only — batch-mates are fulfilled normally (per-request fault
  slices are checked after demux, never batch-wide);
* cancellation/deadlines honored at batch boundaries — the claim into
  a batch is the point of no return;
* graceful ``shutdown(drain=True)`` flushes everything queued, then
  joins every dispatcher.

Multi-device sharding (``devices=``): the service runs a POOL of
per-device executors, each owning its own coalescer queue, its own
dispatcher thread, and — because jit cache entries are per-device — its
own independent warm cache.  A bucket-affinity router pins each
``bucket_key`` to a home device (least-loaded at first sight, sticky
after) so a bucket's one-time compile is paid once and every later
dispatch of that bucket stays warm.  Work stealing migrates a ripened
batch to an idle device when the home is busy or backed up, accepting
the one-time compile on the thief (counted in ``stats()`` as a cold
hit and a steal).  The default ``devices=None`` is the single-executor
path with NO device pinning — byte-identical to the classic
single-device service, sharing the process default-device jit cache.

Bit-identity guarantee (tests/test_serve.py, test_serve_multidevice.py):
a demuxed result equals the solo ``simulate_batch`` run of the same
request under the same normalized cfg, per stat including
``fault_shots`` — REGARDLESS of which device ran it.  The multi path is
the generic engine vmapped over programs, each program's step counter
freezes independently; short requests are padded by replicating their
OWN shot rows and (under ``pad_programs``) batches are padded to a
power-of-two program count by replicating the last request — both inert
under deterministic execution, trimmed off in
:func:`~..sim.interpreter.demux_multi_batch`.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from dataclasses import replace

import numpy as np

import jax

from .. import isa
from ..decoder import stack_machine_programs
from ..sim.interpreter import (ENGINES, InterpreterConfig, FaultError,
                               demux_multi_batch, fault_shot_counts,
                               resolve_engine, simulate_batch,
                               simulate_multi_batch)
from ..utils import profiling
from .batcher import Coalescer, bucket_key
from .request import (CancelledError, QueueFullError, Request,
                      ServiceClosedError)

# dispatcher threads carry this prefix so the test harness can detect
# leaked services (tests/conftest.py prints the junit-gated marker —
# tools/check_junit.py — when one survives a test)
DISPATCH_THREAD_PREFIX = 'dproc-serve-dispatch'

_SERVICE_SEQ = itertools.count()


def _normalize_cfg(cfg: InterpreterConfig, n_instr_bucket: int):
    """One request cfg -> (bucket-keyed jit cfg, strict flag).

    Budgets default from the BUCKET shape exactly like
    ``simulate_multi_batch`` derives them (content-derived budgets
    would fragment the buckets and retrace per ensemble); the engine
    selector is normalized away (multi path is generic-only) and
    'strict' is split out as the per-request host policy.
    """
    if cfg is None:
        cfg = InterpreterConfig(max_steps=2 * n_instr_bucket + 64,
                                max_pulses=n_instr_bucket + 2)
    if cfg.straightline or cfg.engine in ('straightline', 'block',
                                          'pallas'):
        raise ValueError(
            'the execution service coalesces onto the multi-program '
            'generic engine; of the engine ladder (auto / generic / '
            'block / straightline / pallas) the straightline, block '
            'and pallas engines key on program content and cannot '
            'serve a shared batch (use singleton_engine= for '
            '1-program fallback dispatch)')
    if cfg.opcode_histogram:
        raise ValueError(
            'opcode_histogram=True cannot be served: op_hist is summed '
            'over shot lanes inside the jit, so the shot-replication '
            'padding used to coalesce unequal shot counts would '
            'contaminate it (run simulate_batch directly instead)')
    strict = cfg.fault_mode == 'strict'
    if cfg.fault_mode not in ('count', 'strict'):
        raise ValueError(
            f"fault_mode must be 'count' or 'strict'; got "
            f"{cfg.fault_mode!r}")
    if strict or cfg.straightline is None or cfg.engine is not None:
        cfg = replace(cfg, fault_mode='count', straightline=False,
                      engine=None)
    return cfg, strict


def _pad_shots(arr: np.ndarray, n_shots: int) -> np.ndarray:
    """Pad the leading shot axis up to ``n_shots`` by replicating the
    last row — the inert-lane padding ``demux_multi_batch`` trims."""
    if arr.shape[0] == n_shots:
        return arr
    reps = np.repeat(arr[-1:], n_shots - arr.shape[0], axis=0)
    return np.concatenate([arr, reps], axis=0)


def _pow2(n: int) -> int:
    return 1 << (n - 1).bit_length()


def _bucket_label(key: tuple) -> str:
    """Human/JSON-able label for a bucket key: the shape part only
    (cores x instruction bucket).  Distinct cfg/geometry variants of
    the same shape share a label — the per-bucket compile stats answer
    "which SHAPES are hot", not "which exact executables"."""
    return f'c{key[0]}i{key[1]}'


class _DeviceExecutor:
    """One device's slice of the service: its own coalescer queue, its
    own dispatcher thread, its own (per-device, hence independent) warm
    jit cache, and its own counters.  ``device=None`` means "do not pin"
    — the process default device, the classic single-device path.  All
    mutable state is guarded by the service's condition variable; the
    executor is a struct, the service owns the concurrency."""

    def __init__(self, svc: 'ExecutionService', idx: int, device,
                 max_batch_programs: int, max_wait_s: float):
        self.idx = idx
        self.device = device
        self.q = Coalescer(max_batch_programs, max_wait_s)
        self.busy = False            # a batch is executing right now
        self.dispatches = 0
        self.programs_dispatched = 0
        self.occupancy = collections.Counter()          # batch size -> n
        self.engine_dispatches = collections.Counter()  # engine -> n
        self.steals = 0              # batches this executor stole
        self.stolen_from = 0         # batches stolen FROM this executor
        self.cold_compiles = 0
        self.warm_hits = 0
        # (bucket_key, shape signature) dispatched at least once on
        # this device: the host-side cold/warm compile classifier (the
        # jit cache itself keys on the same shapes, per device)
        self.seen = set()
        self.thread = threading.Thread(
            target=svc._dispatch_loop, args=(self,),
            name=f'{DISPATCH_THREAD_PREFIX}-{svc.name}-d{idx}',
            daemon=True)

    def label(self) -> str:
        return 'default' if self.device is None else str(self.device)


class ExecutionService:
    """In-process continuous-batching front end over the interpreter.

    Parameters
    ----------
    cfg:
        Default :class:`InterpreterConfig` for submissions that do not
        bring their own.  ``None`` (default) derives per-bucket budgets
        the same way ``simulate_multi_batch`` does.
    max_batch_programs:
        Coalescing ceiling — a bucket dispatches as soon as it holds
        this many requests.
    max_wait_ms:
        Coalescing deadline — a bucket with fewer requests dispatches
        once its oldest member has waited this long.  The
        latency/throughput dial: 0 approximates per-request dispatch,
        large values maximize occupancy.
    max_queue:
        Admission bound on TOTAL queued requests across buckets and
        devices; ``submit`` raises :class:`QueueFullError` beyond it.
    singleton_engine:
        Optional engine selector ('auto' / 'straightline' / 'block' /
        'pallas' / 'generic') for batches that end up with a single
        program: those gain nothing from the multi path, so they may
        ride :func:`simulate_batch` and the full engine ladder instead.
        Default None keeps everything on the one shared multi-program
        cache (the right call for compile-bound fleets).
    devices:
        How many executors the service shards across.  ``None``
        (default): ONE executor with no device pinning — the classic
        single-device service, regardless of how many devices the host
        advertises.  An int n / ``'all'``: one executor pinned to each
        of the first n / all local devices
        (:func:`~..parallel.mesh.serving_devices`).  Or an explicit
        sequence of jax devices.
    work_stealing:
        Allow an idle executor to migrate a ripened batch away from a
        busy or backed-up home device (one-time compile on the thief,
        counted in stats).  Default True; meaningless with one executor.
    pad_programs:
        Pad each multi-program batch to a power-of-two program count by
        replicating the last request (inert, trimmed at demux) so
        odd-sized remainders and stolen batches reuse the pow2-shaped
        executables instead of compiling one per batch size.  Default
        True.
    """

    def __init__(self, cfg: InterpreterConfig = None, *,
                 max_batch_programs: int = 16, max_wait_ms: float = 2.0,
                 max_queue: int = 256, singleton_engine: str = None,
                 name: str = None, devices=None,
                 work_stealing: bool = True, pad_programs: bool = True):
        if max_batch_programs < 1:
            raise ValueError('max_batch_programs must be >= 1')
        if max_queue < 1:
            raise ValueError('max_queue must be >= 1')
        if singleton_engine is not None and singleton_engine not in ENGINES:
            raise ValueError(
                f'singleton_engine must be one of {ENGINES} or None; '
                f'got {singleton_engine!r}')
        self._default_cfg = cfg
        self.max_batch_programs = max_batch_programs
        self.max_queue = max_queue
        self.singleton_engine = singleton_engine
        self.pad_programs = pad_programs
        self.name = name or f'svc{next(_SERVICE_SEQ)}'
        if devices is None:
            dev_list = [None]
        elif isinstance(devices, bool):
            raise ValueError('devices must be None, an int, "all", or '
                             'a sequence of jax devices')
        elif isinstance(devices, int):
            from ..parallel.mesh import serving_devices
            dev_list = serving_devices(devices)
        elif devices == 'all':
            from ..parallel.mesh import serving_devices
            dev_list = serving_devices()
        else:
            dev_list = list(devices)
            if not dev_list:
                raise ValueError('devices sequence must be non-empty')
        self._cv = threading.Condition()
        self._executors = [
            _DeviceExecutor(self, i, d, max_batch_programs,
                            max_wait_ms / 1e3)
            for i, d in enumerate(dev_list)]
        self._stealing = bool(work_stealing) and len(self._executors) > 1
        self._home = {}                        # bucket_key -> executor idx
        self._home_counts = collections.Counter()
        self._seq = itertools.count()
        self._closing = False
        self._drain = True
        # stats (guarded by _cv's lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0          # FaultError / batch execution errors
        self._cancelled = 0
        self._expired = 0
        self._rejected = 0        # QueueFullError at admission
        self._dispatches = 0
        self._programs_dispatched = 0
        self._steals = 0
        self._warmups = 0
        self._occupancy = collections.Counter()   # batch size -> count
        self._engine_dispatches = collections.Counter()  # engine -> count
        self._bucket_compiles = {}     # bucket label -> {'cold','warm'}
        self._latency_s = collections.deque(maxlen=4096)
        for ex in self._executors:
            ex.thread.start()

    # -- submission ------------------------------------------------------

    def submit(self, mp, meas_bits=None, *, shots: int = None,
               init_regs=None, cfg: InterpreterConfig = None,
               priority: int = 0, deadline_ms: float = None,
               fault_mode: str = None):
        """Queue one program for execution; returns its
        :class:`RequestHandle` immediately.

        ``meas_bits`` is ``[n_shots, n_cores, n_meas]`` (or None with
        ``shots=`` for all-zero measurement feeds); ``init_regs`` is
        None, ``[n_cores, N_REGS]`` (shared across shots) or
        ``[n_shots, n_cores, N_REGS]``.  ``priority`` picks the lane
        (higher dispatches first); ``deadline_ms`` arms a
        relative-to-now deadline enforced at batch boundaries;
        ``fault_mode`` overrides the cfg's ('strict' raises
        :class:`FaultError` on THIS handle only, batch-mates are
        unaffected).
        """
        if meas_bits is None:
            if shots is None:
                raise ValueError('provide meas_bits or shots=')
            n_shots = int(shots)
            if n_shots < 1:
                raise ValueError('shots must be >= 1')
        else:
            meas_bits = np.asarray(meas_bits, np.int32)
            if meas_bits.ndim != 3 or meas_bits.shape[1] != mp.n_cores:
                raise ValueError(
                    f'meas_bits must be [n_shots, n_cores='
                    f'{mp.n_cores}, n_meas]; got '
                    f'{tuple(meas_bits.shape)}')
            if shots is not None and shots != meas_bits.shape[0]:
                raise ValueError(
                    f'shots={shots} contradicts meas_bits shot axis '
                    f'{meas_bits.shape[0]}')
            n_shots = meas_bits.shape[0]
            if n_shots < 1:
                raise ValueError('meas_bits must carry >= 1 shot')
        cfg = cfg if cfg is not None else self._default_cfg
        if fault_mode is not None:
            base = cfg if cfg is not None else InterpreterConfig(
                max_steps=2 * isa.shape_bucket(mp.n_instr) + 64,
                max_pulses=isa.shape_bucket(mp.n_instr) + 2)
            cfg = replace(base, fault_mode=fault_mode)
        cfg, strict = _normalize_cfg(cfg, isa.shape_bucket(mp.n_instr))
        if meas_bits is None:
            meas_bits = np.zeros((n_shots, mp.n_cores, cfg.max_meas),
                                 np.int32)
        elif meas_bits.shape[-1] != cfg.max_meas:
            # normalize the measurement width here (same truncate/zero-
            # pad as the interpreter's _pad_meas) so every member of a
            # bucket stacks into one [P, B, C, max_meas] tensor
            if meas_bits.shape[-1] > cfg.max_meas:
                meas_bits = meas_bits[..., :cfg.max_meas]
            else:
                meas_bits = np.pad(meas_bits, [
                    (0, 0), (0, 0),
                    (0, cfg.max_meas - meas_bits.shape[-1])])
        if init_regs is not None:
            init_regs = np.asarray(init_regs, np.int32)
            if init_regs.ndim == 2:
                init_regs = np.broadcast_to(
                    init_regs[None],
                    (n_shots,) + init_regs.shape).copy()
            if init_regs.ndim != 3 or init_regs.shape != (
                    n_shots, mp.n_cores, isa.N_REGS):
                raise ValueError(
                    f'init_regs must be [n_cores, {isa.N_REGS}] or '
                    f'[n_shots={n_shots}, n_cores={mp.n_cores}, '
                    f'{isa.N_REGS}]; got {tuple(init_regs.shape)}')
        deadline = None if deadline_ms is None \
            else time.monotonic() + deadline_ms / 1e3
        key = bucket_key(mp, cfg)
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
            if self._depth_locked() >= self.max_queue:
                self._rejected += 1
                profiling.counter_inc('serve.rejected')
                raise QueueFullError(
                    f'queue full ({self.max_queue} requests pending)')
            req = Request(mp=mp, meas_bits=meas_bits,
                          init_regs=init_regs, cfg=cfg, strict=strict,
                          n_shots=n_shots, priority=priority,
                          deadline=deadline, seq=next(self._seq))
            self._route_locked(key).q.push(key, req)
            self._submitted += 1
            profiling.counter_inc('serve.submitted')
            self._cv.notify_all()
        return req.handle

    # -- routing / stealing ----------------------------------------------

    def _depth_locked(self) -> int:
        return sum(len(ex.q) for ex in self._executors)

    def _route_locked(self, key) -> _DeviceExecutor:
        """Bucket-affinity router: the first sighting of a bucket pins
        it to the least-loaded executor (queue depth, then how many
        home buckets it already carries, then index — deterministic);
        every later submission of the bucket lands on the same home so
        its warm per-device jit cache stays hot."""
        idx = self._home.get(key)
        if idx is None:
            idx = min(self._executors,
                      key=lambda ex: (len(ex.q),
                                      self._home_counts[ex.idx],
                                      ex.idx)).idx
            self._home[key] = idx
            self._home_counts[idx] += 1
        return self._executors[idx]

    def _try_steal_locked(self, thief: _DeviceExecutor, now: float,
                          flush: bool = False) -> bool:
        """Migrate one ripened batch from the deepest eligible victim
        queue into ``thief``'s.  A victim is eligible when it has a
        ripe bucket it cannot serve promptly: it is mid-execution, or
        more than one bucket ripened at once (or the service is
        draining, when any backlog is fair game).  Returns True when
        requests actually moved; the thief's own pop_batch then claims
        them (``absorb`` re-ran the deadline/cancel checks — a stolen
        request never outlives its deadline silently)."""
        best = None
        for v in self._executors:
            if v is thief or len(v.q) == 0:
                continue
            ripe = v.q.ripe_keys(now, flush=flush)
            if not ripe:
                continue
            if not (flush or v.busy or len(ripe) > 1):
                continue
            if best is None or len(v.q) > len(best[0].q):
                best = (v, ripe[0])
        if best is None:
            return False
        victim, key = best
        reqs = victim.q.migrate_bucket(key, self.max_batch_programs)
        if not reqs:
            return False
        victim.stolen_from += 1
        thief.steals += 1
        self._steals += 1
        profiling.counter_inc('serve.steals')
        expired = thief.q.absorb(key, reqs, now)
        self._count_expired_locked(expired)
        return True

    def _count_expired_locked(self, expired) -> None:
        if expired:
            self._expired += len(expired)
            profiling.counter_inc('serve.expired', len(expired))

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self, ex: _DeviceExecutor):
        while True:
            with self._cv:
                while True:
                    flush = self._closing and self._drain
                    key, batch, expired = ex.q.pop_batch(flush=flush)
                    self._count_expired_locked(expired)
                    if key is None and self._stealing:
                        if self._try_steal_locked(ex, time.monotonic(),
                                                  flush=flush):
                            continue     # absorbed work: pop it now
                    if key is not None:
                        ex.busy = True
                        # wake idle peers: the remaining ripe buckets
                        # just became stealable
                        self._cv.notify_all()
                        break
                    if self._closing and (not self._drain
                                          or self._depth_locked() == 0):
                        return
                    timeout = self._wait_timeout_locked(
                        ex, time.monotonic())
                    if timeout is None:
                        self._cv.wait()
                    elif timeout > 0:
                        self._cv.wait(timeout)
                    else:
                        # something is ripe somewhere but not claimable
                        # by this executor yet: bounded re-check
                        self._cv.wait(0.002)
            try:
                self._execute(ex, key, batch)
            finally:
                with self._cv:
                    ex.busy = False
                    self._cv.notify_all()

    def _wait_timeout_locked(self, ex: _DeviceExecutor,
                             now: float) -> float:
        """Condition-wait horizon: this executor's next queue event,
        or — with stealing on — any peer's (a peer's bucket ripening
        may become this executor's work)."""
        t = ex.q.next_event(now)
        if self._stealing:
            for v in self._executors:
                if v is ex:
                    continue
                tv = v.q.next_event(now)
                if tv is not None:
                    t = tv if t is None else min(t, tv)
        return t

    def _execute(self, ex: _DeviceExecutor, key, batch):
        cfg = key[-1]
        t0 = time.monotonic()
        try:
            results = self._run_batch(ex, key, batch, cfg)
        except Exception as exc:      # noqa: BLE001 - fail the batch, live on
            with self._cv:
                self._failed += len(batch)
            profiling.counter_inc('serve.batch_failures')
            for req in batch:
                req.handle._fail(exc)
            return
        completed = failed = 0
        for req, res in zip(batch, results):
            if req.strict:
                counts = np.asarray(fault_shot_counts(res['fault']))
                if counts.any():
                    req.handle._fail(FaultError(counts))
                    failed += 1
                    continue
            req.handle._fulfill(res)
            completed += 1
        now = time.monotonic()
        with self._cv:
            self._dispatches += 1
            self._programs_dispatched += len(batch)
            self._occupancy[len(batch)] += 1
            ex.dispatches += 1
            ex.programs_dispatched += len(batch)
            ex.occupancy[len(batch)] += 1
            self._completed += completed
            self._failed += failed
            for req in batch:
                self._latency_s.append(now - req.submit_t)
        profiling.counter_inc('serve.dispatches')
        profiling.counter_inc('serve.programs_dispatched', len(batch))
        profiling.counter_inc('serve.batch_ms',
                              int((now - t0) * 1e3))

    def _run_batch(self, ex: _DeviceExecutor, key, batch, cfg):
        """Execute one coalesced batch on ``ex``'s device; returns
        per-request stats dicts in batch order (host numpy, padding
        trimmed)."""
        if len(batch) == 1 and self.singleton_engine is not None:
            req = batch[0]
            scfg = replace(cfg, engine=self.singleton_engine)
            eng = resolve_engine(req.mp, scfg)
            self._count_engine_locked(ex, eng)
            self._classify_compile(ex, key, ('solo', eng, req.n_shots,
                                             req.init_regs is None))
            out = simulate_batch(req.mp, req.meas_bits, req.init_regs,
                                 cfg=scfg, jax_device=ex.device)
            return [jax.tree.map(np.asarray, out)]
        B = max(r.n_shots for r in batch)
        P = _pow2(len(batch)) if self.pad_programs else len(batch)
        pad = P - len(batch)
        # program-count padding replicates the LAST request: its lanes
        # are deterministic copies, and demux only reads the first
        # len(batch) program slots — inert, but it keeps odd-sized
        # remainders and stolen batches on the pow2-shaped executables
        meas = np.stack(
            [_pad_shots(r.meas_bits, B) for r in batch]
            + [_pad_shots(batch[-1].meas_bits, B)] * pad)
        if any(r.init_regs is not None for r in batch):
            rows = [_pad_shots(r.init_regs, B) if r.init_regs is not None
                    else np.zeros((B, r.mp.n_cores, isa.N_REGS), np.int32)
                    for r in batch]
            init = np.stack(rows + [rows[-1]] * pad)
        else:
            init = None
        mmp = stack_machine_programs(
            [r.mp for r in batch] + [batch[-1].mp] * pad,
            pad_to=key_bucket(batch))
        self._count_engine_locked(ex, 'generic')
        self._classify_compile(ex, key, ('multi', P, B, init is None))
        out = simulate_multi_batch(mmp, meas, init, cfg=cfg,
                                   jax_device=ex.device)
        host = jax.tree.map(np.asarray, out)
        return [demux_multi_batch(host, i, n_shots=r.n_shots)
                for i, r in enumerate(batch)]

    def _count_engine_locked(self, ex: _DeviceExecutor, eng: str):
        """Record which ladder rung a dispatch actually ran on (the
        multi path is generic by construction; the singleton path
        resolves 'auto' the same way ``simulate_batch`` will)."""
        with self._cv:
            self._engine_dispatches[eng] += 1
            ex.engine_dispatches[eng] += 1
        profiling.counter_inc(f'serve.engine.{eng}')

    def _classify_compile(self, ex: _DeviceExecutor, key,
                          shape_sig: tuple) -> bool:
        """Host-side cold/warm jit classification: the first dispatch
        of a (bucket, shape signature) on a device is a compile, every
        repeat is a warm cache hit — the same shapes the jit cache
        itself keys on, tracked per executor because cache entries are
        per device.  (An estimate: a process-shared persistent compile
        cache can make a "cold" entry cheap, and content-keyed
        singleton engines can recompile under an unchanged signature.)
        Groundwork for the ROADMAP AOT-warmup item via :meth:`warmup`.
        """
        sig = (key, shape_sig)
        with self._cv:
            cold = sig not in ex.seen
            if cold:
                ex.seen.add(sig)
                ex.cold_compiles += 1
            else:
                ex.warm_hits += 1
            per = self._bucket_compiles.setdefault(
                _bucket_label(key), {'cold': 0, 'warm': 0})
            per['cold' if cold else 'warm'] += 1
        profiling.counter_inc(
            'serve.compile.cold' if cold else 'serve.compile.warm')
        return cold

    # -- warmup ----------------------------------------------------------

    def warmup(self, mp, *, shots: int = 1, n_programs: int = None,
               cfg: InterpreterConfig = None) -> list:
        """Pre-compile ``mp``'s bucket on EVERY device executor by
        running one representative batch synchronously, so the first
        real request in the bucket does not eat the XLA compile inside
        its latency budget (the ROADMAP "AOT warmup" groundwork — and
        the reason cold/warm hits are tracked at all).

        The jit cache keys on the full batch SHAPE — (programs, shots,
        cores, instruction bucket, cfg) — so warm coverage needs
        representative ``shots`` and ``n_programs`` (default
        ``max_batch_programs``; padded to a power of two exactly like
        live dispatch when ``pad_programs``).  Counted in
        ``stats()['compile']`` and the ``serve.compile.*`` counters
        like any dispatch.  Returns per-executor
        ``{'device', 'cold'}`` dicts."""
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
        n_programs = n_programs if n_programs is not None \
            else self.max_batch_programs
        n_programs = max(1, min(n_programs, self.max_batch_programs))
        base = cfg if cfg is not None else self._default_cfg
        ncfg, _ = _normalize_cfg(base, isa.shape_bucket(mp.n_instr))
        meas = np.zeros((int(shots), mp.n_cores, ncfg.max_meas),
                        np.int32)
        key = bucket_key(mp, ncfg)
        batch = [Request(mp=mp, meas_bits=meas, init_regs=None,
                         cfg=ncfg, strict=False, n_shots=int(shots),
                         priority=0, deadline=None, seq=-1)
                 for _ in range(n_programs)]
        report = []
        for ex in self._executors:
            seen0 = ex.cold_compiles
            self._run_batch(ex, key, batch, ncfg)
            with self._cv:
                self._warmups += 1
                cold = ex.cold_compiles > seen0
            profiling.counter_inc('serve.warmups')
            report.append({'device': ex.label(), 'cold': cold})
        return report

    # -- introspection / lifecycle ---------------------------------------

    def stats(self) -> dict:
        """Snapshot of the service counters: aggregate queue depth,
        batch occupancy histogram, coalescing efficiency (programs per
        dispatch), p50/p99 submit-to-done latency in ms, cold/warm jit
        compile hits per bucket, and a per-device breakdown (queue
        depth, occupancy, steals, compile hits) for the multi-device
        pool."""
        with self._cv:
            lat = np.asarray(self._latency_s, np.float64)
            occ = dict(sorted(self._occupancy.items()))
            devices = [{
                'device': ex.label(),
                'index': ex.idx,
                'busy': ex.busy,
                'queue_depth': len(ex.q),
                'dispatches': ex.dispatches,
                'programs_dispatched': ex.programs_dispatched,
                'batch_occupancy': dict(sorted(ex.occupancy.items())),
                'engine_dispatches': dict(sorted(
                    ex.engine_dispatches.items())),
                'steals': ex.steals,
                'stolen_from': ex.stolen_from,
                'cold_compiles': ex.cold_compiles,
                'warm_hits': ex.warm_hits,
                'home_buckets': self._home_counts[ex.idx],
            } for ex in self._executors]
            snap = {
                'queue_depth': self._depth_locked(),
                'submitted': self._submitted,
                'completed': self._completed,
                'failed': self._failed,
                'cancelled': self._cancelled + sum(
                    ex.q.dropped_cancelled for ex in self._executors),
                'expired': self._expired,
                'rejected': self._rejected,
                'dispatches': self._dispatches,
                'programs_dispatched': self._programs_dispatched,
                'batch_occupancy': occ,
                'engine_dispatches': dict(sorted(
                    self._engine_dispatches.items())),
                'coalesce_efficiency': (
                    self._programs_dispatched / self._dispatches
                    if self._dispatches else 0.0),
                'n_devices': len(self._executors),
                'work_stealing': self._stealing,
                'steals': self._steals,
                'warmups': self._warmups,
                'compile': {
                    'cold': sum(ex.cold_compiles
                                for ex in self._executors),
                    'warm': sum(ex.warm_hits
                                for ex in self._executors),
                    'per_bucket': {k: dict(v) for k, v in sorted(
                        self._bucket_compiles.items())},
                },
                'devices': devices,
            }
        if lat.size:
            snap['latency_p50_ms'] = float(np.percentile(lat, 50) * 1e3)
            snap['latency_p99_ms'] = float(np.percentile(lat, 99) * 1e3)
        else:
            snap['latency_p50_ms'] = snap['latency_p99_ms'] = 0.0
        snap['latency_samples'] = int(lat.size)
        return snap

    def shutdown(self, drain: bool = True, timeout: float = None):
        """Stop the service.  ``drain=True`` (default) flushes every
        queued request through dispatch first (all executors keep
        draining — including by stealing — until every queue is empty);
        ``drain=False`` fails queued requests with
        :class:`CancelledError` (in-flight batches still complete).
        Joins every dispatcher thread (up to ``timeout`` seconds EACH);
        idempotent."""
        with self._cv:
            if not self._closing:
                self._closing = True
                self._drain = drain
                if not drain:
                    for ex in self._executors:
                        n = ex.q.cancel_all(CancelledError(
                            f'service {self.name!r} shut down without '
                            f'draining'))
                        self._cancelled += n
                        if n:
                            profiling.counter_inc('serve.cancelled', n)
            self._cv.notify_all()
        for ex in self._executors:
            ex.thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.shutdown(drain=exc_info[0] is None)


def key_bucket(batch) -> int:
    """The instruction bucket every member of a coalesced batch pads
    into — identical across the batch by construction (it is part of
    the coalescing key)."""
    return isa.shape_bucket(batch[0].mp.n_instr)
