"""The continuous-batching execution service.

:class:`ExecutionService` is the in-process serving runtime over the
interpreter: any thread calls :meth:`~ExecutionService.submit` with one
compiled :class:`~..decoder.MachineProgram` and gets a
:class:`~.request.RequestHandle` back immediately; a single dispatcher
thread drains the queue, coalesces compatible requests into
shape-bucketed batches (``batcher.bucket_key``), runs each batch
through :func:`~..sim.interpreter.simulate_multi_batch` — hitting the
warm jit cache keyed on the bucket SHAPE — and demuxes per-request
stats back onto the handles.  The classic continuous-batching contract
(vLLM-style, transplanted from token generation to shot execution):

* latency/throughput dial: a bucket dispatches when it reaches
  ``max_batch_programs`` or its oldest member has waited
  ``max_wait_ms``;
* admission control: a bounded queue (``max_queue``) makes overload a
  synchronous :class:`QueueFullError` at submit, not unbounded growth;
* isolation: ``fault_mode='strict'`` raises
  :class:`~..sim.interpreter.FaultError` on the OFFENDING request's
  handle only — batch-mates are fulfilled normally (per-request fault
  slices are checked after demux, never batch-wide);
* cancellation/deadlines honored at batch boundaries — the claim into
  a batch is the point of no return;
* graceful ``shutdown(drain=True)`` flushes everything queued, then
  joins the dispatcher.

Bit-identity guarantee (tests/test_serve.py): a demuxed result equals
the solo ``simulate_batch`` run of the same request under the same
normalized cfg, per stat including ``fault_shots`` — the multi path is
the generic engine vmapped over programs, each program's step counter
freezes independently, and short requests are padded by replicating
their OWN shot rows (inert under deterministic execution, trimmed off
in :func:`~..sim.interpreter.demux_multi_batch`).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from dataclasses import replace

import numpy as np

import jax

from .. import isa
from ..decoder import stack_machine_programs
from ..sim.interpreter import (ENGINES, InterpreterConfig, FaultError,
                               demux_multi_batch, fault_shot_counts,
                               resolve_engine, simulate_batch,
                               simulate_multi_batch)
from ..utils import profiling
from .batcher import Coalescer, bucket_key
from .request import (CancelledError, QueueFullError, Request,
                      ServiceClosedError)

# dispatcher threads carry this prefix so the test harness can detect
# leaked services (tests/conftest.py prints the junit-gated marker —
# tools/check_junit.py — when one survives a test)
DISPATCH_THREAD_PREFIX = 'dproc-serve-dispatch'

_SERVICE_SEQ = itertools.count()


def _normalize_cfg(cfg: InterpreterConfig, n_instr_bucket: int):
    """One request cfg -> (bucket-keyed jit cfg, strict flag).

    Budgets default from the BUCKET shape exactly like
    ``simulate_multi_batch`` derives them (content-derived budgets
    would fragment the buckets and retrace per ensemble); the engine
    selector is normalized away (multi path is generic-only) and
    'strict' is split out as the per-request host policy.
    """
    if cfg is None:
        cfg = InterpreterConfig(max_steps=2 * n_instr_bucket + 64,
                                max_pulses=n_instr_bucket + 2)
    if cfg.straightline or cfg.engine in ('straightline', 'block',
                                          'pallas'):
        raise ValueError(
            'the execution service coalesces onto the multi-program '
            'generic engine; of the engine ladder (auto / generic / '
            'block / straightline / pallas) the straightline, block '
            'and pallas engines key on program content and cannot '
            'serve a shared batch (use singleton_engine= for '
            '1-program fallback dispatch)')
    if cfg.opcode_histogram:
        raise ValueError(
            'opcode_histogram=True cannot be served: op_hist is summed '
            'over shot lanes inside the jit, so the shot-replication '
            'padding used to coalesce unequal shot counts would '
            'contaminate it (run simulate_batch directly instead)')
    strict = cfg.fault_mode == 'strict'
    if cfg.fault_mode not in ('count', 'strict'):
        raise ValueError(
            f"fault_mode must be 'count' or 'strict'; got "
            f"{cfg.fault_mode!r}")
    if strict or cfg.straightline is None or cfg.engine is not None:
        cfg = replace(cfg, fault_mode='count', straightline=False,
                      engine=None)
    return cfg, strict


def _pad_shots(arr: np.ndarray, n_shots: int) -> np.ndarray:
    """Pad the leading shot axis up to ``n_shots`` by replicating the
    last row — the inert-lane padding ``demux_multi_batch`` trims."""
    if arr.shape[0] == n_shots:
        return arr
    reps = np.repeat(arr[-1:], n_shots - arr.shape[0], axis=0)
    return np.concatenate([arr, reps], axis=0)


class ExecutionService:
    """In-process continuous-batching front end over the interpreter.

    Parameters
    ----------
    cfg:
        Default :class:`InterpreterConfig` for submissions that do not
        bring their own.  ``None`` (default) derives per-bucket budgets
        the same way ``simulate_multi_batch`` does.
    max_batch_programs:
        Coalescing ceiling — a bucket dispatches as soon as it holds
        this many requests.
    max_wait_ms:
        Coalescing deadline — a bucket with fewer requests dispatches
        once its oldest member has waited this long.  The
        latency/throughput dial: 0 approximates per-request dispatch,
        large values maximize occupancy.
    max_queue:
        Admission bound on TOTAL queued requests across buckets;
        ``submit`` raises :class:`QueueFullError` beyond it.
    singleton_engine:
        Optional engine selector ('auto' / 'straightline' / 'block' /
        'pallas' / 'generic') for batches that end up with a single
        program: those gain nothing from the multi path, so they may
        ride :func:`simulate_batch` and the full engine ladder instead.
        Default None keeps everything on the one shared multi-program
        cache (the right call for compile-bound fleets).
    """

    def __init__(self, cfg: InterpreterConfig = None, *,
                 max_batch_programs: int = 16, max_wait_ms: float = 2.0,
                 max_queue: int = 256, singleton_engine: str = None,
                 name: str = None):
        if max_batch_programs < 1:
            raise ValueError('max_batch_programs must be >= 1')
        if max_queue < 1:
            raise ValueError('max_queue must be >= 1')
        if singleton_engine is not None and singleton_engine not in ENGINES:
            raise ValueError(
                f'singleton_engine must be one of {ENGINES} or None; '
                f'got {singleton_engine!r}')
        self._default_cfg = cfg
        self.max_queue = max_queue
        self.singleton_engine = singleton_engine
        self.name = name or f'svc{next(_SERVICE_SEQ)}'
        self._cv = threading.Condition()
        self._q = Coalescer(max_batch_programs, max_wait_ms / 1e3)
        self._seq = itertools.count()
        self._closing = False
        self._drain = True
        # stats (guarded by _cv's lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0          # FaultError / batch execution errors
        self._cancelled = 0
        self._expired = 0
        self._rejected = 0        # QueueFullError at admission
        self._dispatches = 0
        self._programs_dispatched = 0
        self._occupancy = collections.Counter()   # batch size -> count
        self._engine_dispatches = collections.Counter()  # engine -> count
        self._latency_s = collections.deque(maxlen=4096)
        self._thread = threading.Thread(
            target=self._dispatch_loop,
            name=f'{DISPATCH_THREAD_PREFIX}-{self.name}', daemon=True)
        self._thread.start()

    # -- submission ------------------------------------------------------

    def submit(self, mp, meas_bits=None, *, shots: int = None,
               init_regs=None, cfg: InterpreterConfig = None,
               priority: int = 0, deadline_ms: float = None,
               fault_mode: str = None):
        """Queue one program for execution; returns its
        :class:`RequestHandle` immediately.

        ``meas_bits`` is ``[n_shots, n_cores, n_meas]`` (or None with
        ``shots=`` for all-zero measurement feeds); ``init_regs`` is
        None, ``[n_cores, N_REGS]`` (shared across shots) or
        ``[n_shots, n_cores, N_REGS]``.  ``priority`` picks the lane
        (higher dispatches first); ``deadline_ms`` arms a
        relative-to-now deadline enforced at batch boundaries;
        ``fault_mode`` overrides the cfg's ('strict' raises
        :class:`FaultError` on THIS handle only, batch-mates are
        unaffected).
        """
        if meas_bits is None:
            if shots is None:
                raise ValueError('provide meas_bits or shots=')
            n_shots = int(shots)
            if n_shots < 1:
                raise ValueError('shots must be >= 1')
        else:
            meas_bits = np.asarray(meas_bits, np.int32)
            if meas_bits.ndim != 3 or meas_bits.shape[1] != mp.n_cores:
                raise ValueError(
                    f'meas_bits must be [n_shots, n_cores='
                    f'{mp.n_cores}, n_meas]; got '
                    f'{tuple(meas_bits.shape)}')
            if shots is not None and shots != meas_bits.shape[0]:
                raise ValueError(
                    f'shots={shots} contradicts meas_bits shot axis '
                    f'{meas_bits.shape[0]}')
            n_shots = meas_bits.shape[0]
            if n_shots < 1:
                raise ValueError('meas_bits must carry >= 1 shot')
        cfg = cfg if cfg is not None else self._default_cfg
        if fault_mode is not None:
            base = cfg if cfg is not None else InterpreterConfig(
                max_steps=2 * isa.shape_bucket(mp.n_instr) + 64,
                max_pulses=isa.shape_bucket(mp.n_instr) + 2)
            cfg = replace(base, fault_mode=fault_mode)
        cfg, strict = _normalize_cfg(cfg, isa.shape_bucket(mp.n_instr))
        if meas_bits is None:
            meas_bits = np.zeros((n_shots, mp.n_cores, cfg.max_meas),
                                 np.int32)
        elif meas_bits.shape[-1] != cfg.max_meas:
            # normalize the measurement width here (same truncate/zero-
            # pad as the interpreter's _pad_meas) so every member of a
            # bucket stacks into one [P, B, C, max_meas] tensor
            if meas_bits.shape[-1] > cfg.max_meas:
                meas_bits = meas_bits[..., :cfg.max_meas]
            else:
                meas_bits = np.pad(meas_bits, [
                    (0, 0), (0, 0),
                    (0, cfg.max_meas - meas_bits.shape[-1])])
        if init_regs is not None:
            init_regs = np.asarray(init_regs, np.int32)
            if init_regs.ndim == 2:
                init_regs = np.broadcast_to(
                    init_regs[None],
                    (n_shots,) + init_regs.shape).copy()
            if init_regs.ndim != 3 or init_regs.shape != (
                    n_shots, mp.n_cores, isa.N_REGS):
                raise ValueError(
                    f'init_regs must be [n_cores, {isa.N_REGS}] or '
                    f'[n_shots={n_shots}, n_cores={mp.n_cores}, '
                    f'{isa.N_REGS}]; got {tuple(init_regs.shape)}')
        deadline = None if deadline_ms is None \
            else time.monotonic() + deadline_ms / 1e3
        key = bucket_key(mp, cfg)
        with self._cv:
            if self._closing:
                raise ServiceClosedError(
                    f'service {self.name!r} is shut down')
            if len(self._q) >= self.max_queue:
                self._rejected += 1
                profiling.counter_inc('serve.rejected')
                raise QueueFullError(
                    f'queue full ({self.max_queue} requests pending)')
            req = Request(mp=mp, meas_bits=meas_bits,
                          init_regs=init_regs, cfg=cfg, strict=strict,
                          n_shots=n_shots, priority=priority,
                          deadline=deadline, seq=next(self._seq))
            self._q.push(key, req)
            self._submitted += 1
            profiling.counter_inc('serve.submitted')
            self._cv.notify_all()
        return req.handle

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self):
        while True:
            with self._cv:
                while True:
                    flush = self._closing and self._drain
                    key, batch, expired = self._q.pop_batch(flush=flush)
                    if expired:
                        self._expired += len(expired)
                        profiling.counter_inc('serve.expired',
                                              len(expired))
                    if key is not None:
                        break
                    if self._closing and (not self._drain
                                          or len(self._q) == 0):
                        return
                    timeout = self._q.next_event()
                    if timeout is None or timeout > 0:
                        self._cv.wait(timeout)
                    # timeout == 0.0: a bucket is already ripe, loop
            self._execute(key, batch)

    def _execute(self, key, batch):
        cfg = key[-1]
        t0 = time.monotonic()
        try:
            results = self._run_batch(batch, cfg)
        except Exception as exc:      # noqa: BLE001 - fail the batch, live on
            with self._cv:
                self._failed += len(batch)
            profiling.counter_inc('serve.batch_failures')
            for req in batch:
                req.handle._fail(exc)
            return
        completed = failed = 0
        for req, res in zip(batch, results):
            if req.strict:
                counts = np.asarray(fault_shot_counts(res['fault']))
                if counts.any():
                    req.handle._fail(FaultError(counts))
                    failed += 1
                    continue
            req.handle._fulfill(res)
            completed += 1
        now = time.monotonic()
        with self._cv:
            self._dispatches += 1
            self._programs_dispatched += len(batch)
            self._occupancy[len(batch)] += 1
            self._completed += completed
            self._failed += failed
            for req in batch:
                self._latency_s.append(now - req.submit_t)
        profiling.counter_inc('serve.dispatches')
        profiling.counter_inc('serve.programs_dispatched', len(batch))
        profiling.counter_inc('serve.batch_ms',
                              int((now - t0) * 1e3))

    def _run_batch(self, batch, cfg):
        """Execute one coalesced batch; returns per-request stats dicts
        in batch order (host numpy, padding trimmed)."""
        if len(batch) == 1 and self.singleton_engine is not None:
            req = batch[0]
            scfg = replace(cfg, engine=self.singleton_engine)
            self._count_engine(resolve_engine(req.mp, scfg))
            out = simulate_batch(req.mp, req.meas_bits, req.init_regs,
                                 cfg=scfg)
            return [jax.tree.map(np.asarray, out)]
        B = max(r.n_shots for r in batch)
        meas = np.stack([_pad_shots(r.meas_bits, B) for r in batch])
        if any(r.init_regs is not None for r in batch):
            init = np.stack([
                _pad_shots(r.init_regs, B) if r.init_regs is not None
                else np.zeros((B, r.mp.n_cores, isa.N_REGS), np.int32)
                for r in batch])
        else:
            init = None
        mmp = stack_machine_programs([r.mp for r in batch],
                                     pad_to=key_bucket(batch))
        self._count_engine('generic')
        out = simulate_multi_batch(mmp, meas, init, cfg=cfg)
        host = jax.tree.map(np.asarray, out)
        return [demux_multi_batch(host, i, n_shots=r.n_shots)
                for i, r in enumerate(batch)]

    def _count_engine(self, eng: str):
        """Record which ladder rung a dispatch actually ran on (the
        multi path is generic by construction; the singleton path
        resolves 'auto' the same way ``simulate_batch`` will)."""
        with self._cv:
            self._engine_dispatches[eng] += 1
        profiling.counter_inc(f'serve.engine.{eng}')

    # -- introspection / lifecycle ---------------------------------------

    def stats(self) -> dict:
        """Snapshot of the service counters: queue depth, batch
        occupancy histogram, coalescing efficiency (programs per
        dispatch), and p50/p99 submit-to-done latency in ms."""
        with self._cv:
            lat = np.asarray(self._latency_s, np.float64)
            occ = dict(sorted(self._occupancy.items()))
            snap = {
                'queue_depth': len(self._q),
                'submitted': self._submitted,
                'completed': self._completed,
                'failed': self._failed,
                'cancelled': self._cancelled + self._q.dropped_cancelled,
                'expired': self._expired,
                'rejected': self._rejected,
                'dispatches': self._dispatches,
                'programs_dispatched': self._programs_dispatched,
                'batch_occupancy': occ,
                'engine_dispatches': dict(sorted(
                    self._engine_dispatches.items())),
                'coalesce_efficiency': (
                    self._programs_dispatched / self._dispatches
                    if self._dispatches else 0.0),
            }
        if lat.size:
            snap['latency_p50_ms'] = float(np.percentile(lat, 50) * 1e3)
            snap['latency_p99_ms'] = float(np.percentile(lat, 99) * 1e3)
        else:
            snap['latency_p50_ms'] = snap['latency_p99_ms'] = 0.0
        snap['latency_samples'] = int(lat.size)
        return snap

    def shutdown(self, drain: bool = True, timeout: float = None):
        """Stop the service.  ``drain=True`` (default) flushes every
        queued request through dispatch first; ``drain=False`` fails
        queued requests with :class:`CancelledError` (in-flight batches
        still complete).  Joins the dispatcher thread (up to
        ``timeout`` seconds); idempotent."""
        with self._cv:
            if not self._closing:
                self._closing = True
                self._drain = drain
                if not drain:
                    n = self._q.cancel_all(CancelledError(
                        f'service {self.name!r} shut down without '
                        f'draining'))
                    self._cancelled += n
                    profiling.counter_inc('serve.cancelled', n)
            self._cv.notify_all()
        self._thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.shutdown(drain=exc_info[0] is None)


def key_bucket(batch) -> int:
    """The instruction bucket every member of a coalesced batch pads
    into — identical across the batch by construction (it is part of
    the coalescing key)."""
    return isa.shape_bucket(batch[0].mp.n_instr)
