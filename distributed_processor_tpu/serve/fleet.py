"""Fleet: replica process manager + shared warm tiers + front door.

The top of the serving stack (docs/FLEET.md): a :class:`Fleet` spawns N
:mod:`.replica_main` processes (each one :class:`ExecutionService` on
the :mod:`.transport` wire), registers them with a
:class:`~.router.FleetRouter`, and keeps the population at N — a
monitor thread detects dead replica processes (SIGKILL, OOM, crash) and
respawns them with the SAME replica id, so the router sees a
``replica_down`` followed by a ``replica_up`` on a fresh connection.

Every replica of a fleet shares three warm tiers under ``shared_dir``:

* ``xla/`` — the JAX persistent compilation cache,
* ``compile/`` — the serve-tier content-addressed
  :class:`~.compile_cache.PersistentStore`,
* ``catalog.json`` — the learned AOT warmup :class:`~.catalog.
  BucketCatalog` (flock-guarded, merge-on-write, so concurrent
  replicas interleave safely).

A respawned replica therefore replays its warmup from what its PEERS
compiled: its first served request hits zero cold compiles — the
fleet's answer to the cold-start problem the single-service AOT warmup
solved in-process.

Chaos hooks (``kill`` / ``wedge`` / ``unwedge``) drive the fleet soak:
SIGKILL exercises connection-loss failover, SIGSTOP exercises the
gossip-staleness path (the TCP connection stays open while the process
makes no progress), SIGCONT exercises heartbeat re-admission.

SLO-driven elasticity (docs/FLEET.md "Autoscaling",
docs/SERVING.md "Tenants"): with ``autoscale=`` configured, the
monitor thread closes the loop from the router's SLO watch — replica
count scales UP on sustained breach (fleet-wide stage or per-tenant
``'tenant:<name>'`` budget) and DOWN on sustained slack, through
:class:`AutoscalePolicy`'s hysteresis band (sustain windows + action
cooldown) so a noisy p99 cannot flap the population.  Scale-up lands
warm because new replicas replay the shared tiers; scale-down fails
the victim's in-flight work over through the ordinary
``remove_replica`` path before the process dies.
"""

from __future__ import annotations

import json
import os
import select
import signal
import subprocess
import sys
import tempfile
import threading
import time

from ..utils import profiling
from .router import ROUTER_THREAD_PREFIX, FleetRouter

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class AutoscalePolicy:
    """Hysteresis band between the SLO level signal and scaling acts.

    Pure decision logic (no threads, no clock of its own) so tests
    drive it with synthetic time: feed ``decide(breached, n, now)``
    the router's current breach level and population each tick; it
    answers ``'up'`` / ``'down'`` / ``None``.  An action requires the
    signal to SUSTAIN (``breach_s`` of continuous breach, ``slack_s``
    of continuous slack) AND the cooldown since the last action to
    have elapsed — two independent anti-flap guards, so one noisy p99
    sample can neither scale up nor immediately undo a scale-up.
    Population stays inside ``[min_replicas, max_replicas]``.
    """

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 breach_sustain_s: float = 1.0,
                 slack_sustain_s: float = 5.0,
                 cooldown_s: float = 2.0):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f'need 1 <= min_replicas <= max_replicas; got '
                f'[{min_replicas}, {max_replicas}]')
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.breach_sustain_s = float(breach_sustain_s)
        self.slack_sustain_s = float(slack_sustain_s)
        self.cooldown_s = float(cooldown_s)
        self._breach_since = None
        self._slack_since = None
        self._last_action_t = None

    def decide(self, breached: bool, n: int, now: float):
        if breached:
            self._slack_since = None
            if self._breach_since is None:
                self._breach_since = now
            if (now - self._breach_since >= self.breach_sustain_s
                    and self._cool(now) and n < self.max_replicas):
                self._act(now)
                return 'up'
            return None
        self._breach_since = None
        if self._slack_since is None:
            self._slack_since = now
        if (now - self._slack_since >= self.slack_sustain_s
                and self._cool(now) and n > self.min_replicas):
            self._act(now)
            return 'down'
        return None

    def _cool(self, now: float) -> bool:
        return self._last_action_t is None \
            or now - self._last_action_t >= self.cooldown_s

    def _act(self, now: float) -> None:
        # an action consumes the sustained window: the signal must
        # re-sustain before the NEXT action, on top of the cooldown
        self._last_action_t = now
        self._breach_since = None
        self._slack_since = None

    def snapshot(self) -> dict:
        return {'min_replicas': self.min_replicas,
                'max_replicas': self.max_replicas,
                'breach_sustain_s': self.breach_sustain_s,
                'slack_sustain_s': self.slack_sustain_s,
                'cooldown_s': self.cooldown_s}


class _ReplicaProc:
    __slots__ = ('rid', 'proc', 'address', 'log_path', 'spawned_t',
                 'wedged', 'respawns')

    def __init__(self, rid):
        self.rid = rid
        self.proc = None
        self.address = None
        self.log_path = None
        self.spawned_t = 0.0
        self.wedged = False
        self.respawns = 0


class Fleet:
    """N supervised replica processes behind one FleetRouter.

    ``submit`` / ``submit_source`` / ``stats`` mirror the service API;
    handles resolve bit-identical-or-typed across replica loss.  The
    ``service`` dict is passed to every replica's ExecutionService
    (JSON-able kwargs only: ``devices``, ``max_est_wait_ms``,
    ``breaker_*``, ...); ``interp_cfg`` likewise for the default
    InterpreterConfig.  ``env`` overrides the replicas' environment
    (platform / device-count knobs are applied before jax imports).
    """

    def __init__(self, n_replicas: int = 2, *, shared_dir: str = None,
                 service: dict = None, interp_cfg: dict = None,
                 env: dict = None, respawn: bool = True,
                 respawn_backoff_s: float = 0.25,
                 monitor_interval_s: float = 0.05,
                 ready_timeout_s: float = 300.0,
                 name: str = None, router_kwargs: dict = None,
                 trace_sample: float = 0.0, slo_budgets: dict = None,
                 integrity: bool = False, tenants: dict = None,
                 autoscale=None):
        if n_replicas < 1:
            raise ValueError('n_replicas must be >= 1')
        self.name = name or 'fleet'
        self._tmp = None
        if shared_dir is None:
            self._tmp = tempfile.TemporaryDirectory(
                prefix='dproc-fleet-')
            shared_dir = self._tmp.name
        self.shared_dir = shared_dir
        os.makedirs(os.path.join(shared_dir, 'logs'), exist_ok=True)
        self._service = dict(service or {})
        self._interp_cfg = dict(interp_cfg) if interp_cfg else None
        self._env = dict(env or {})
        self._respawn = bool(respawn)
        self._respawn_backoff_s = respawn_backoff_s
        self._monitor_interval_s = monitor_interval_s
        self._ready_timeout_s = ready_timeout_s
        # fleet observability: the ROUTER samples (its decision rides
        # the wire, so replicas trace exactly the sampled set without
        # their own sampling rate); trace_sample also reaches replicas
        # so locally-originated diagnostics share the same knob
        router_kwargs = dict(router_kwargs or {})
        if trace_sample:
            router_kwargs.setdefault('trace_sample', trace_sample)
            self._service.setdefault('trace_sample', trace_sample)
        if slo_budgets:
            router_kwargs.setdefault('slo_budgets', dict(slo_budgets))
        if integrity:
            # end-to-end digests across the wire (docs/ROBUSTNESS.md
            # "Integrity"): submit-time program CRC verified by the
            # replica, replica-stamped result digest verified here
            router_kwargs.setdefault('integrity', True)
        if tenants:
            # one tenant config for the whole fleet: every replica
            # enforces the same weights/quotas, so a tenant cannot
            # route around its limits by landing on another replica
            # (docs/SERVING.md "Tenants")
            self._service.setdefault('tenants', dict(tenants))
        # SLO-driven elasticity: dict of AutoscalePolicy kwargs, an
        # AutoscalePolicy instance, or True for defaults; None = off
        if autoscale is True:
            autoscale = AutoscalePolicy()
        elif isinstance(autoscale, dict):
            autoscale = AutoscalePolicy(**autoscale)
        self._autoscale = autoscale
        self._scale_ups = 0
        self._scale_downs = 0
        self.router = FleetRouter(name=self.name, **router_kwargs)
        self._lock = threading.Lock()
        self._closing = False
        self._replicas = [_ReplicaProc(f'r{i}')
                          for i in range(n_replicas)]
        try:
            self._spawn_all()
        except BaseException:
            self.shutdown()
            raise
        self._monitor = threading.Thread(
            target=self._monitor_loop,
            name=f'{ROUTER_THREAD_PREFIX}-monitor-{self.name}',
            daemon=True)
        self._monitor.start()

    # -- spawning --------------------------------------------------------

    def _replica_config(self, rid: str) -> dict:
        cfg = {
            'rid': rid,
            'env': self._env,
            'jax_cache_dir': os.path.join(self.shared_dir, 'xla'),
            'service': dict(self._service),
        }
        cfg['service'].setdefault(
            'compile_cache_dir', os.path.join(self.shared_dir,
                                              'compile'))
        cfg['service'].setdefault(
            'warmup_catalog', os.path.join(self.shared_dir,
                                           'catalog.json'))
        if self._interp_cfg:
            cfg['interp_cfg'] = self._interp_cfg
        return cfg

    def _spawn(self, slot: _ReplicaProc) -> None:
        env = dict(os.environ)
        env['PYTHONPATH'] = _PKG_ROOT + os.pathsep \
            + env.get('PYTHONPATH', '')
        env.update({k: str(v) for k, v in self._env.items()})
        slot.log_path = os.path.join(
            self.shared_dir, 'logs',
            f'{slot.rid}.{slot.respawns}.log')
        log = open(slot.log_path, 'wb')
        try:
            proc = subprocess.Popen(
                [sys.executable, '-m',
                 'distributed_processor_tpu.serve.replica_main',
                 json.dumps(self._replica_config(slot.rid))],
                stdout=subprocess.PIPE, stderr=log, env=env,
                cwd=_PKG_ROOT)
        finally:
            log.close()
        ready = self._read_ready(slot, proc)
        slot.proc = proc
        slot.address = (ready['host'], ready['port'])
        slot.spawned_t = time.monotonic()
        slot.wedged = False
        self.router.add_replica(slot.rid, slot.address)

    def _read_ready(self, slot, proc) -> dict:
        """Block (bounded) for the replica's JSON ready line."""
        deadline = time.monotonic() + self._ready_timeout_s
        buf = b''
        fd = proc.stdout.fileno()
        while b'\n' not in buf:
            remain = deadline - time.monotonic()
            if remain <= 0 or proc.poll() is not None:
                proc.kill()
                raise RuntimeError(
                    f'replica {slot.rid} failed to become ready '
                    f'(exit={proc.poll()}): {self._log_tail(slot)}')
            r, _, _ = select.select([fd], [], [], min(remain, 1.0))
            if r:
                chunk = os.read(fd, 4096)
                if not chunk:
                    continue
                buf += chunk
        return json.loads(buf.split(b'\n', 1)[0])

    def _log_tail(self, slot, n: int = 2000) -> str:
        try:
            with open(slot.log_path, 'rb') as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - n))
                return f.read().decode('utf-8', 'replace')
        except OSError:
            return '<no log>'

    def _spawn_all(self) -> None:
        # replicas import jax independently — spawn concurrently so
        # fleet startup is one replica's boot time, not the sum
        errs = []

        def boot(slot):
            try:
                self._spawn(slot)
            except BaseException as exc:   # noqa: BLE001
                errs.append((slot.rid, exc))

        threads = [threading.Thread(
            target=boot, args=(s,),
            name=f'{ROUTER_THREAD_PREFIX}-spawn-{s.rid}', daemon=True)
            for s in self._replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errs:
            raise RuntimeError(
                f'fleet spawn failed: {errs[0][0]}: {errs[0][1]}')

    # -- supervision -----------------------------------------------------

    def _monitor_loop(self) -> None:
        while True:
            with self._lock:
                if self._closing:
                    return
                slots = list(self._replicas)
            for slot in slots:
                proc = slot.proc
                # proc None = a scale-up slot whose first spawn
                # failed; retry it like a death
                if proc is not None and proc.poll() is None:
                    continue
                if self._closing or not self._respawn:
                    continue
                time.sleep(self._respawn_backoff_s)
                with self._lock:
                    if self._closing:
                        return
                    if slot not in self._replicas:
                        continue    # scaled away during the backoff
                slot.respawns += 1
                try:
                    self._spawn(slot)
                except RuntimeError:
                    # spawn failed (e.g. mid-shutdown): retry next tick
                    pass
            self._autoscale_tick()
            time.sleep(self._monitor_interval_s)

    def _autoscale_tick(self) -> None:
        """One elasticity decision on the monitor cadence: integrate
        the router's SLO level signal through the policy's hysteresis
        and apply at most one single-step scaling action."""
        policy = self._autoscale
        if policy is None or self._closing:
            return
        with self._lock:
            n = len(self._replicas)
        act = policy.decide(self.router.slo_breached(), n,
                            time.monotonic())
        if act == 'up':
            self.scale_to(n + 1, reason='slo-breach')
        elif act == 'down':
            self.scale_to(n - 1, reason='slo-slack')

    def scale_to(self, n: int, reason: str = 'manual') -> int:
        """Set the replica population to ``n``: spawn fresh replicas
        (they land warm off the shared tiers) or retire the
        highest-index ones — a retired replica's in-flight work fails
        over through :meth:`~.router.FleetRouter.remove_replica`
        BEFORE its process dies, so scale-down loses nothing.  Returns
        the new population.  Edge-triggered ``autoscale_up`` /
        ``autoscale_down`` flight events make every scaling act
        visible in the incident timeline."""
        n = max(1, int(n))
        with self._lock:
            if self._closing:
                return len(self._replicas)
            cur = len(self._replicas)
            if n == cur:
                return cur
            if n > cur:
                grown = [_ReplicaProc(f'r{i}') for i in range(cur, n)]
                self._replicas.extend(grown)
                victims = []
                self._scale_ups += 1
            else:
                grown = []
                victims = self._replicas[n:]
                del self._replicas[n:]
                self._scale_downs += 1
        direction = 'up' if grown else 'down'
        profiling.counter_inc(f'fleet.autoscale_{direction}')
        self.router.flight_recorder.record(
            f'autoscale_{direction}', reason=reason,
            n_from=cur, n_to=n)
        for slot in grown:
            try:
                self._spawn(slot)
            except RuntimeError:
                pass            # monitor retries on its next tick
        for slot in victims:
            self.router.remove_replica(slot.rid)
            proc = slot.proc
            if proc is None:
                continue
            try:
                os.kill(proc.pid, signal.SIGCONT)   # unwedge first
            except OSError:
                pass
            proc.terminate()
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
            if proc.stdout is not None:
                proc.stdout.close()
        return n

    # -- chaos hooks -----------------------------------------------------

    def pid(self, idx: int) -> int:
        return self._replicas[idx].proc.pid

    def kill(self, idx: int) -> None:
        """SIGKILL a replica process: connection-loss failover (the
        monitor respawns it when ``respawn=True``)."""
        self._replicas[idx].proc.kill()

    def wedge(self, idx: int) -> None:
        """SIGSTOP a replica: it stops making progress while its TCP
        connection stays open — only gossip staleness can catch it."""
        os.kill(self._replicas[idx].proc.pid, signal.SIGSTOP)
        self._replicas[idx].wedged = True

    def unwedge(self, idx: int) -> None:
        """SIGCONT a wedged replica: its next heartbeat re-admits it."""
        os.kill(self._replicas[idx].proc.pid, signal.SIGCONT)
        self._replicas[idx].wedged = False

    # -- serving API -----------------------------------------------------

    def submit(self, *args, **kw):
        return self.router.submit(*args, **kw)

    def submit_source(self, *args, **kw):
        return self.router.submit_source(*args, **kw)

    # streaming sessions (docs/SERVING.md "Streaming sessions"):
    # process-backed streams route exactly like the router's — the
    # fleet adds supervised respawn of a session's home replica
    def open_stream(self, *args, **kw):
        return self.router.open_stream(*args, **kw)

    def submit_rounds(self, *args, **kw):
        return self.router.submit_rounds(*args, **kw)

    def close_stream(self, sid: int) -> bool:
        return self.router.close_stream(sid)

    def replica_ids(self) -> list:
        return [s.rid for s in self._replicas]

    def replica_stats(self, idx_or_rid) -> dict:
        rid = idx_or_rid if isinstance(idx_or_rid, str) \
            else self._replicas[idx_or_rid].rid
        return self.router.call_replica(rid, 'stats')

    # -- fleet observability (docs/OBSERVABILITY.md) ---------------------

    def set_trace_sample(self, sample: float) -> None:
        self.router.set_trace_sample(sample)

    def prometheus_text(self) -> str:
        """Merged fleet exposition: every replica's metrics with a
        ``replica`` label + rollups + the router's own fleet metrics."""
        return self.router.prometheus_text()

    def merged_flight(self, pull: bool = True) -> dict:
        """Federated flight-recorder timeline (router + replicas)."""
        return self.router.merged_flight(pull=pull)

    def dump_trace(self, path: str) -> int:
        """Write the stitched fleet Chrome Trace; returns event count."""
        return self.router.dump_trace(path)

    def stats(self) -> dict:
        snap = self.router.stats()
        with self._lock:
            snap['processes'] = {
                s.rid: {
                    'pid': s.proc.pid if s.proc else None,
                    'running': s.proc is not None
                    and s.proc.poll() is None,
                    'wedged': s.wedged,
                    'respawns': s.respawns,
                } for s in self._replicas}
            snap['autoscale'] = {
                'enabled': self._autoscale is not None,
                'scale_ups': self._scale_ups,
                'scale_downs': self._scale_downs,
                'policy': self._autoscale.snapshot()
                if self._autoscale is not None else None,
            }
        snap['shared_dir'] = self.shared_dir
        return snap

    # -- teardown --------------------------------------------------------

    def shutdown(self, timeout_s: float = 20.0) -> None:
        """Stop the monitor, gracefully stop every replica (escalating
        to SIGKILL), shut the router down, clean the temp shared dir.
        Idempotent; after it returns no fleet thread or process
        remains."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
        if getattr(self, '_monitor', None) is not None:
            self._monitor.join(timeout=5.0)
        deadline = time.monotonic() + timeout_s
        for slot in self._replicas:
            proc = slot.proc
            if proc is None or proc.poll() is not None:
                continue
            try:
                os.kill(proc.pid, signal.SIGCONT)   # unwedge first
            except OSError:
                pass
            try:
                self.router.call_replica(slot.rid, 'shutdown',
                                         timeout_s=2.0)
            except Exception:          # noqa: BLE001 - escalate below
                pass
            proc.terminate()
        for slot in self._replicas:
            proc = slot.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=5.0)
            if proc.stdout is not None:
                proc.stdout.close()
        self.router.shutdown()
        if self._tmp is not None:
            self._tmp.cleanup()
            self._tmp = None

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.shutdown()
