"""Supervision policy objects for the self-healing execution service.

The service (``service.py``) runs a supervisor thread that health-checks
every device executor: a heartbeat per dispatch-loop iteration, a
wall-clock watchdog around every device dispatch (a hung XLA call is
*detected*, not waited out), and dead-thread detection with dispatcher
respawn.  This module holds the two policy objects that parameterize it
— pure data + arithmetic, no threads, no locks (the service owns the
concurrency, exactly like :class:`~.batcher.Coalescer`):

* :class:`RetryPolicy` — how many times an INFRASTRUCTURE failure
  (executor crash, hang, dead dispatcher — classified by
  :func:`~..sim.interpreter.is_infrastructure_error`) may be retried,
  and the exponential backoff between attempts.  Program-class errors
  (:class:`~..sim.interpreter.FaultError`, validation, bad arguments)
  are NEVER retried: they reproduce identically on any executor.
* :class:`CircuitBreaker` — the per-executor trip state machine:

  ::

      live --(threshold consecutive infra failures,
              or a hang / dead thread)--> quarantined
      quarantined --(cooldown elapsed)--> probing (half-open)
      probing --(canary ok, bit-identical)--> live   [re-admitted]
      probing --(canary failed)--> quarantined       [cooldown doubles]

  While quarantined/probing the executor receives no routed traffic
  and may not steal; its sticky buckets and queued backlog re-home to
  healthy executors through the existing migrate/absorb path (which
  re-runs every deadline/cancel check), and its in-flight batch is
  retried elsewhere under the :class:`RetryPolicy`.

docs/ROBUSTNESS.md "serving-layer failures" has the full taxonomy
table (which errors retry, which propagate) and the shedding policy.
"""

from __future__ import annotations

from dataclasses import dataclass

# executor health states (stats()['devices'][i]['health'])
HEALTH_LIVE = 'live'
HEALTH_QUARANTINED = 'quarantined'
HEALTH_PROBING = 'probing'          # half-open: canary in flight


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded retry of infrastructure failures.

    ``max_attempts`` counts EXECUTIONS, not retries: 3 means the
    original dispatch plus at most two retries; 1 disables retrying.
    When the budget is exhausted the request fails with the ORIGINAL
    infrastructure error (the first one it hit), never a generic
    "gave up".  Backoff is exponential with a cap: retry *k* (0-based)
    waits ``min(backoff_s * backoff_mult**k, max_backoff_s)`` parked
    outside the dispatch queues, so a crashing executor cannot
    hot-loop a doomed batch.
    """
    max_attempts: int = 3
    backoff_s: float = 0.02
    backoff_mult: float = 2.0
    max_backoff_s: float = 1.0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError('max_attempts must be >= 1')
        if self.backoff_s < 0 or self.max_backoff_s < 0:
            raise ValueError('backoff must be >= 0')

    def delay_s(self, retry_index: int) -> float:
        """Backoff before retry ``retry_index`` (0-based)."""
        return min(self.backoff_s * self.backoff_mult ** retry_index,
                   self.max_backoff_s)


class CircuitBreaker:
    """Per-executor breaker bookkeeping (state lives here, transitions
    are driven by the service under its lock).

    Counts CONSECUTIVE infrastructure failures — any successful batch
    resets the streak.  ``trip`` arms the cooldown and escalates it
    (each successive trip doubles the wait, capped), ``readmit``
    resets the streak and restores the base cooldown.
    """

    def __init__(self, threshold: int = 3, cooldown_s: float = 0.25,
                 cooldown_mult: float = 2.0, max_cooldown_s: float = 30.0):
        if threshold < 1:
            raise ValueError('threshold must be >= 1')
        self.threshold = threshold
        self.base_cooldown_s = cooldown_s
        self.cooldown_mult = cooldown_mult
        self.max_cooldown_s = max_cooldown_s
        self.consecutive = 0
        self.trips = 0
        self.readmissions = 0
        self.cooldown_until = None
        self._next_cooldown_s = cooldown_s

    def record_failure(self) -> bool:
        """Count one infrastructure failure; True when the streak just
        reached the trip threshold (the caller quarantines)."""
        self.consecutive += 1
        return self.consecutive >= self.threshold

    def record_success(self) -> None:
        self.consecutive = 0

    def trip(self, now: float) -> None:
        """Arm (or re-arm, escalating) the cooldown."""
        self.trips += 1
        self.cooldown_until = now + self._next_cooldown_s
        self._next_cooldown_s = min(
            self._next_cooldown_s * self.cooldown_mult,
            self.max_cooldown_s)

    def ready_to_probe(self, now: float) -> bool:
        return self.cooldown_until is not None \
            and now >= self.cooldown_until

    def readmit(self) -> None:
        self.readmissions += 1
        self.consecutive = 0
        self.cooldown_until = None
        self._next_cooldown_s = self.base_cooldown_s

    def snapshot(self) -> dict:
        """JSON-able breaker state — the payload the service attaches
        to ``breaker_trip`` flight-recorder events
        (docs/OBSERVABILITY.md)."""
        return {'trips': self.trips,
                'consecutive': self.consecutive,
                'readmissions': self.readmissions,
                'next_cooldown_s': self._next_cooldown_s}
