"""FleetRouter: the front door that load-balances across replicas.

One router process fans ``submit`` / ``submit_source`` traffic out over
N :class:`~.service.ExecutionService` replicas (separate processes,
reached through :mod:`.transport`), and keeps serving — bit-identical
or typed — while replicas die, hang, or restart (docs/FLEET.md).  The
design deliberately re-uses the in-process supervision vocabulary one
ring out:

* **Health gossip.**  A gossip thread polls every replica's ``stats()``
  digest (queue depth, est_wait, health mix) on ``gossip_interval_ms``.
  Each response re-arms the replica's heartbeat; a replica whose last
  heartbeat exceeds ``liveness_window_ms`` is declared down
  (``gossip_stale`` + ``replica_down`` flight events) even when its TCP
  connection still accepts bytes — the wedged-process case a connection
  error can never surface.  A stale replica that beats again (a SIGCONT
  after a wedge) is simply re-admitted: its recovered requests already
  completed elsewhere, and the stale wire callbacks were forgotten, so
  resuming routing to it is safe.
* **Fleet-level circuit breakers.**  Each replica carries a
  :class:`~.supervise.CircuitBreaker`; consecutive infrastructure
  failures attributed to it (connection loss, ``OverloadError``, chaos
  crashes) quarantine it for the breaker cooldown, and the first
  heartbeat after cooldown re-admits it.
* **Cross-replica retry.**  In-flight requests on a dead replica are
  recovered from the router's shadow ledger and re-dispatched to a
  surviving replica under the shared :class:`~.supervise.RetryPolicy`:
  attempts are bounded, backoff is exponential, exhaustion surfaces the
  ORIGINAL infrastructure error.  Typed program-class errors
  (``FaultError``, validation — :func:`is_infrastructure_error`) and
  terminal request outcomes (``DeadlineError``, ``CancelledError`` /
  ``ShutdownError``) are NEVER retried.  Every dispatch carries an
  attempt token (mirroring :class:`~.request.RequestHandle`'s claim
  tokens): a straggling response or failure report whose token went
  stale is a silent no-op, so a request can never be double-completed
  or double-retried no matter how wire callbacks interleave.
* **Bucket affinity.**  Placement is sticky per
  :class:`~.bucketspec.BucketSpec` coalescing template: a bucket's home
  replica keeps its jit/AOT caches hot, exactly like the per-device
  sticky-bucket map inside the service; ties break to the least-loaded
  live replica by gossiped est_wait / queue depth.

The router owns no execution and no devices — it is restartable state:
everything here rebuilds from replicas' gossip within one interval.
"""

from __future__ import annotations

import collections
import heapq
import itertools
import threading
import time

import numpy as np

from ..integrity import IntegrityError, program_digest, stats_digest
from ..sim.interpreter import is_infrastructure_error
from ..utils import profiling
from ..obs import (ClockOffsetEstimator, FlightRecorder, Histogram,
                   Tracer, merged_prometheus_text,
                   prometheus_snapshot_lines, write_chrome_trace)
from .. import isa
from .batcher import bucket_key
from .request import (CancelledError, DeadlineError, RequestHandle,
                      ServiceClosedError, ShutdownError)
from .stream import StreamSession
from .supervise import CircuitBreaker, RetryPolicy
from .transport import ReplicaClient, ReplicaLostError

ROUTER_THREAD_PREFIX = 'dproc-serve-fleet'


def is_terminal_error(exc: BaseException) -> bool:
    """True when a failed attempt must surface to the caller instead of
    retrying on another replica: program-class errors reproduce
    anywhere (:func:`is_infrastructure_error` False), and expired
    deadlines / cancellations are properties of the REQUEST's clock —
    infrastructure-class by taxonomy, but re-execution cannot
    un-expire them."""
    return (not is_infrastructure_error(exc)
            or isinstance(exc, (DeadlineError, CancelledError)))


class _FleetRequest:
    """Router-side shadow of one submission: everything needed to
    re-dispatch it on another replica (the full payload), plus the
    retry ledger.  ``attempts`` doubles as the attempt token — each
    dispatch bumps it, and response/failure handlers that present a
    stale ``(rid, token)`` pair are dropped."""

    __slots__ = ('handle', 'op', 'payload', 'key', 'attempts',
                 'first_error', 'excluded', 'submit_t', 'rid',
                 'wire_id', 'done', 'trace', 'sent_t')

    def __init__(self, op, payload, key):
        self.handle = RequestHandle()
        self.op = op
        self.payload = payload
        self.key = key
        self.attempts = 0           # executions started == token
        self.first_error = None     # original infra error, kept for
        self.excluded = set()       # exhaustion (RetryPolicy rule)
        self.submit_t = time.monotonic()
        self.rid = None             # replica of the CURRENT attempt
        self.wire_id = None
        self.done = False
        self.trace = None           # router-side TraceContext or None
        self.sent_t = None          # wire send time of CURRENT attempt


class _Replica:
    __slots__ = ('rid', 'client', 'breaker', 'alive', 'quarantined',
                 'last_beat', 'digest', 'inflight', 'gossip_pending',
                 'reconnect_t')

    def __init__(self, rid, client, breaker):
        self.rid = rid
        self.client = client
        self.breaker = breaker
        self.alive = True
        self.quarantined = False
        self.last_beat = time.monotonic()
        self.digest = {}
        self.inflight = {}          # wire_id -> (_FleetRequest, token)
        self.gossip_pending = False
        self.reconnect_t = 0.0      # last re-dial attempt (throttle)

    def routable(self) -> bool:
        return self.alive and not self.quarantined \
            and self.client is not None and self.client.alive

    def load(self) -> tuple:
        # gossiped load: est_wait (None sorts as 0) then queue depth
        ew = self.digest.get('est_wait_ms') or 0.0
        return (float(ew), int(self.digest.get('queue_depth') or 0),
                len(self.inflight))


class FleetRouter:
    """Load-balancing, self-healing front door over replica clients.

    Replicas register through :meth:`add_replica` (the
    :class:`~.fleet.Fleet` process manager calls it at spawn and
    respawn); ``submit``/``submit_source`` mirror the service's
    signatures and return local :class:`RequestHandle`\\ s fulfilled
    from wire responses.  ``shutdown`` fails everything still pending
    with :class:`ShutdownError` — after it returns no handle can block
    forever, same contract as the service.
    """

    def __init__(self, *, default_cfg=None, retry_policy=None,
                 gossip_interval_ms: float = 25.0,
                 liveness_window_ms: float = 250.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_ms: float = 500.0,
                 name: str = None, flight_events: int = 512,
                 trace_sample: float = 0.0, trace_keep: int = 1024,
                 slo_budgets: dict = None,
                 slo_min_samples: int = 16,
                 integrity: bool = False):
        if liveness_window_ms <= gossip_interval_ms:
            raise ValueError('liveness window must exceed the gossip '
                             'interval (one missed beat is not death)')
        self.name = name or 'fleet'
        self._default_cfg = default_cfg
        self._retry_policy = retry_policy or RetryPolicy()
        self._gossip_interval_s = gossip_interval_ms / 1e3
        self._liveness_window_s = liveness_window_ms / 1e3
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_ms / 1e3
        self.flight_recorder = FlightRecorder(flight_events)
        self._latency_h = Histogram('fleet.latency_ms')
        # fleet observability (docs/OBSERVABILITY.md "Fleet
        # observability"): the router makes the sampling decision,
        # ships it on the wire, and stitches the replica's spans back
        # into the same context; per-replica clock offsets come from
        # the gossip heartbeat RTT; per-stage histograms feed the SLO
        # watch evaluated on the gossip cadence
        self._tracer = Tracer(trace_sample, keep=trace_keep)
        self._clock: dict = {}          # rid -> ClockOffsetEstimator
        self._stage_h: dict = {}        # stage name -> Histogram
        self._flight_cache: dict = {}   # rid -> last ring digest/pull
        self._slo_budgets = dict(slo_budgets or {})
        self._slo_min_samples = int(slo_min_samples)
        # integrity fabric (docs/ROBUSTNESS.md "Integrity"): stamp a
        # program content digest on every submit (the replica verifies
        # it survived the pickle round trip) and verify the replica's
        # result-stat digest on every reply — a mismatch becomes a
        # retryable IntegrityError, never delivered bits
        self._integrity = bool(integrity)
        self._slo_state: dict = {}      # stage -> currently-breached
        self._slo_last: dict = {}       # stage -> last evaluation
        self._slo_breaches = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._replicas: dict = {}       # rid -> _Replica
        self._home: dict = {}           # bucket identity -> rid
        self._pending: list = []        # heap of (eligible_t, seq, freq)
        self._pending_seq = 0
        self._closing = False
        # counters (written under _lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._retries = 0
        self._retry_exhausted = 0
        self._failovers = 0             # requests recovered off a dead
        self._replica_down = 0          # replica and re-queued
        self._replica_up = 0
        self._gossip_stale = 0
        self._breaker_trips = 0
        self._readmissions = 0
        # streaming sessions (docs/SERVING.md "Streaming sessions"):
        # the router keeps its OWN session registry — chunks reach the
        # replica as detached rounds submissions, and stickiness comes
        # from the ('stream', sid) home key, so a replica death steals
        # the whole session to a new home without replica-side state
        self._stream_seq = itertools.count()
        self._stream_sessions: set = set()
        self._stream_rounds = 0
        self._gossip_thread = threading.Thread(
            target=self._gossip_loop,
            name=f'{ROUTER_THREAD_PREFIX}-gossip-{self.name}',
            daemon=True)
        self._retry_thread = threading.Thread(
            target=self._retry_loop,
            name=f'{ROUTER_THREAD_PREFIX}-retry-{self.name}',
            daemon=True)
        self._gossip_thread.start()
        self._retry_thread.start()

    # -- replica membership ---------------------------------------------

    def add_replica(self, rid: str, address) -> None:
        """Connect to (or reconnect to a respawned) replica at
        ``address`` and start routing to it."""
        client = ReplicaClient(
            address,
            # late-bound `client`: the loss guard must name the exact
            # connection that died, so a replaced client's death can
            # never take down its successor
            on_lost=lambda exc: self._replica_lost(rid, exc,
                                                   via=client))
        with self._lock:
            old = self._replicas.get(rid)
        if old is not None and old.alive:
            # replacing a live replica: fail its in-flight work over
            # first so nothing is silently dropped
            self._replica_lost(rid, ReplicaLostError(f'{rid} replaced'))
        with self._lock:
            old = self._replicas.get(rid)
            self._replicas[rid] = _Replica(
                rid, client,
                CircuitBreaker(self._breaker_threshold,
                               self._breaker_cooldown_s))
            self._replica_up += 1
            self._cv.notify_all()
        if old is not None and old.client is not None:
            old.client.close()
        profiling.counter_inc('fleet.replica_up')
        self.flight_recorder.record('replica_up', rid=rid,
                                    address=list(address))

    def remove_replica(self, rid: str) -> None:
        """Forget a replica (fleet scale-down): in-flight work fails
        over exactly as if it died."""
        self._replica_lost(rid, ReplicaLostError(f'{rid} removed'))
        with self._lock:
            rep = self._replicas.pop(rid, None)
        if rep is not None and rep.client is not None:
            rep.client.close()

    def replica_ids(self) -> list:
        with self._lock:
            return sorted(self._replicas)

    def primary_replica(self):
        """The routable replica carrying the most load right now
        (in-flight wire requests, then gossiped queue depth, then home
        buckets) — chaos tooling kills THIS one so the fault always
        lands on the serving path, even when bucket affinity has
        pinned a single-bucket workload to one home."""
        with self._lock:
            homes = collections.Counter(self._home.values())
            live = [r for r in self._replicas.values()
                    if r.routable()]
            if not live:
                return None
            best = max(live, key=lambda r: (
                len(r.inflight),
                int(r.digest.get('queue_depth') or 0),
                homes[r.rid], r.rid))
            return best.rid

    def call_replica(self, rid: str, op: str = 'stats', payload=None,
                     timeout_s: float = 30.0):
        """Synchronous wire call to ONE specific replica (fleet tests
        and tooling inspect individual replicas this way — e.g. the
        warmed-respawn assertion reads the new replica's compile
        counters directly)."""
        with self._lock:
            rep = self._replicas.get(rid)
            client = rep.client if rep is not None else None
        if client is None:
            raise KeyError(f'unknown replica {rid!r}')
        return client.call(op, payload or {}, timeout_s=timeout_s)

    # -- submission ------------------------------------------------------

    def submit(self, mp, meas_bits=None, *, shots: int = None,
               init_regs=None, cfg=None, priority: int = 0,
               deadline_ms: float = None,
               fault_mode: str = None,
               tenant: str = None) -> RequestHandle:
        payload = dict(mp=mp, meas_bits=meas_bits, shots=shots,
                       init_regs=init_regs,
                       cfg=cfg if cfg is not None else self._default_cfg,
                       priority=priority, deadline_ms=deadline_ms,
                       fault_mode=fault_mode, tenant=tenant)
        if self._integrity:
            payload['_crc'] = program_digest(mp)
        return self._enqueue('submit', payload,
                             self._affinity_key(mp, payload['cfg']))

    def submit_source(self, program, qchip, *, shots: int = None,
                      meas_bits=None, init_regs=None, cfg=None,
                      priority: int = 0, deadline_ms: float = None,
                      fault_mode: str = None, n_qubits: int = 8,
                      pad_to: int = None,
                      tenant: str = None) -> RequestHandle:
        payload = dict(program=program, qchip=qchip, shots=shots,
                       meas_bits=meas_bits, init_regs=init_regs,
                       cfg=cfg if cfg is not None else self._default_cfg,
                       priority=priority, deadline_ms=deadline_ms,
                       fault_mode=fault_mode, n_qubits=n_qubits,
                       pad_to=pad_to, tenant=tenant)
        # no machine program yet, so no bucket: least-loaded placement
        return self._enqueue('submit_source', payload, None)

    # -- streaming (docs/SERVING.md "Streaming sessions") ----------------

    def open_stream(self, mp, *, cfg=None, decode=None,
                    round_deadline_ms: float = None, priority: int = 0,
                    fault_mode: str = None,
                    tenant: str = None) -> StreamSession:
        """Open a fleet-served streaming session: every round chunk is
        one ``submit_rounds`` wire frame and every result one
        incremental resolve frame, so the stream rides the ordinary
        replica protocol unchanged.  The session's home REPLICA is
        sticky via its ``('stream', sid)`` placement key; chunks reach
        the replica as detached rounds submissions (the replica holds
        no session state), so a chaos-killed home simply moves the
        session — in-flight chunks are recovered by the shadow ledger
        and the attempt tokens keep results exactly-once."""
        with self._lock:
            if self._closing:
                raise ServiceClosedError(
                    f'fleet router {self.name!r} is shut down')
            sid = next(self._stream_seq)
            self._stream_sessions.add(sid)
        profiling.counter_inc('fleet.stream.sessions_opened')
        self.flight_recorder.record('stream_open', sid=sid,
                                    router=self.name)
        return StreamSession(self, mp, sid, cfg=cfg, decode=decode,
                             round_deadline_ms=round_deadline_ms,
                             priority=priority, fault_mode=fault_mode,
                             tenant=tenant)

    def submit_rounds(self, mp, meas_bits, *, init_regs=None, cfg=None,
                      decode=None, priority: int = 0,
                      deadline_ms: float = None,
                      round_deadline_ms: float = None,
                      fault_mode: str = None,
                      stream: int = None,
                      tenant: str = None) -> RequestHandle:
        """Route one R-round chunk (``meas_bits`` ``[rounds, n_shots,
        n_cores, n_meas]``) to the stream's home replica — or
        least-loaded placement for a detached (``stream=None``)
        chunk."""
        meas_bits = np.asarray(meas_bits, np.int32)
        if meas_bits.ndim != 4:
            raise ValueError(
                f'submit_rounds meas_bits must be [rounds, n_shots, '
                f'n_cores, n_meas]; got shape {meas_bits.shape}')
        key = None
        if stream is not None:
            with self._lock:
                if stream not in self._stream_sessions:
                    raise ValueError(
                        f'stream {stream} is not open on router '
                        f'{self.name!r} (closed or never opened)')
            key = ('stream', int(stream))
        payload = dict(mp=mp, meas_bits=meas_bits, init_regs=init_regs,
                       cfg=cfg if cfg is not None else self._default_cfg,
                       decode=decode, priority=priority,
                       deadline_ms=deadline_ms,
                       round_deadline_ms=round_deadline_ms,
                       fault_mode=fault_mode, tenant=tenant)
        if self._integrity:
            payload['_crc'] = program_digest(mp)
        handle = self._enqueue('submit_rounds', payload, key)
        with self._lock:
            self._stream_rounds += int(meas_bits.shape[0])
        profiling.counter_inc('fleet.stream.rounds_submitted',
                              int(meas_bits.shape[0]))
        return handle

    def close_stream(self, sid: int) -> bool:
        """Deregister a streaming session and drop its home pin.
        Idempotent; returns whether the session was open."""
        with self._lock:
            present = sid in self._stream_sessions
            self._stream_sessions.discard(sid)
            self._home.pop(('stream', sid), None)
        if present:
            profiling.counter_inc('fleet.stream.sessions_closed')
        return present

    def _affinity_key(self, mp, cfg):
        """The bucket-affinity identity: the same unbound BucketSpec
        template the replica's coalescer will key on.  Any failure to
        compute it (odd cfg, validation the replica will surface typed)
        degrades to least-loaded placement, never an error."""
        try:
            from .service import _normalize_cfg
            ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mp.n_instr))
            return bucket_key(mp, ncfg).identity()
        except Exception:               # noqa: BLE001
            return None

    def _enqueue(self, op, payload, key) -> RequestHandle:
        freq = _FleetRequest(op, payload, key)
        ctx = self._tracer.maybe_start()
        if ctx is not None:
            # the id + decision ride the wire so the replica opens a
            # context for exactly this request; the stitched result
            # lands back on this same context at response time
            freq.trace = ctx
            freq.handle._trace = ctx
            payload['_trace'] = ctx.trace_id
            ctx.instant('submit', t=freq.submit_t, op=op,
                        router=self.name)
        with self._lock:
            if self._closing:
                raise ServiceClosedError(
                    f'fleet router {self.name!r} is shut down')
            self._submitted += 1
        profiling.counter_inc('fleet.submitted')
        self._dispatch(freq)
        return freq.handle

    # -- placement / dispatch -------------------------------------------

    def _place_locked(self, freq):
        live = [r for r in self._replicas.values() if r.routable()]
        candidates = [r for r in live if r.rid not in freq.excluded] \
            or live                     # all excluded: any live one
        if not candidates:
            return None
        if freq.key is not None:
            home = self._home.get(freq.key)
            for r in candidates:
                if r.rid == home:
                    return r
        best = min(candidates, key=lambda r: (r.load(), r.rid))
        if freq.key is not None:
            self._home[freq.key] = best.rid
        return best

    def _dispatch(self, freq) -> None:
        """Place and send one request; parks it (the retry pump re-tries
        placement) when no replica is routable right now."""
        t_place = time.monotonic()
        with self._lock:
            if freq.done:
                return
            if self._closing:
                self._fail_locked(freq, ShutdownError(
                    f'fleet router {self.name!r} is shut down'))
                return
            rep = self._place_locked(freq)
            if rep is None:
                if freq.trace is not None:
                    freq.trace.instant('park',
                                       reason='no-routable-replica')
                self._park_locked(freq, time.monotonic() + 0.02)
                return
            freq.attempts += 1
            token = freq.attempts
            freq.rid = rep.rid
            freq.wire_id = None
            client = rep.client
        ctx = freq.trace
        if ctx is not None:
            ctx.span('route', t_place, time.monotonic(), rid=rep.rid,
                     attempt=token)
        # stamp the send time BEFORE the send: the response callback
        # (another thread) reads it for the wire.await span, and may
        # fire before call_async even returns
        freq.sent_t = t_send = time.monotonic()
        try:
            wire_id = client.call_async(
                freq.op, freq.payload,
                lambda ok, resp: self._on_response(
                    freq, rep.rid, token, ok, resp))
            if ctx is not None:
                ctx.span('wire.send', t_send, time.monotonic(),
                         rid=rep.rid, attempt=token)
        except ReplicaLostError as exc:
            # the send failed (the client's loss path may have already
            # routed this attempt through _on_response — the token
            # guard makes this call a no-op in that case)
            self._attempt_failed(freq, rep.rid, token, exc)
            return
        with self._lock:
            r = self._replicas.get(rep.rid)
            if (not freq.done and freq.attempts == token
                    and freq.rid == rep.rid
                    and r is not None and r.client is client):
                freq.wire_id = wire_id
                r.inflight[wire_id] = (freq, token)

    def _park_locked(self, freq, eligible_t: float) -> None:
        self._pending_seq += 1
        heapq.heappush(self._pending,
                       (eligible_t, self._pending_seq, freq))
        self._cv.notify_all()

    # -- responses / failures -------------------------------------------

    def _stale(self, freq, rid, token) -> bool:
        # caller holds _lock: a report about attempt `token` on `rid`
        # is stale once the request completed, moved on to another
        # attempt, or was already failed-over off this replica
        return freq.done or freq.attempts != token or freq.rid != rid

    def _on_response(self, freq, rid, token, ok, payload) -> None:
        t_resp = time.monotonic()
        piggyback = None
        if ok and isinstance(payload, dict) and '__trace__' in payload:
            # replica-side spans piggybacked on the resolve reply
            # (transport docstring).  Unwrap unconditionally: the
            # replica may have sampled this request on its own even
            # when the router did not
            piggyback = payload['__trace__']
            payload = payload['result']
        if ok and isinstance(payload, dict) and '__icrc__' in payload:
            # replica-stamped result digest (innermost wrapper): a
            # stat block that mutated anywhere between the replica's
            # stamp and here fails verification and takes the
            # cross-replica retry path instead of reaching the handle
            want = payload['__icrc__']
            payload = payload['result']
            try:
                good = stats_digest(payload) == want
            except Exception:           # noqa: BLE001 - mangled stats
                good = False
            if not good:
                profiling.counter_inc('integrity.wire_checksum_fail')
                self.flight_recorder.record('integrity_violation',
                                            rid=rid,
                                            boundary='result-digest')
                ok = False
                payload = IntegrityError(
                    f'result-stat digest mismatch from replica {rid}: '
                    f'corrupted between replica stamp and router')
        with self._lock:
            if self._stale(freq, rid, token):
                return
            rep = self._replicas.get(rid)
            if rep is not None and freq.wire_id is not None:
                rep.inflight.pop(freq.wire_id, None)
            if ok:
                freq.done = True
                self._completed += 1
                if rep is not None:
                    rep.breaker.record_success()
                lat_ms = (time.monotonic() - freq.submit_t) * 1e3
        if ok:
            if freq.trace is not None:
                self._stitch(freq, rid, piggyback, t_resp)
            self._latency_h.observe(lat_ms)
            self._observe_stage('total', lat_ms)
            # per-tenant latency rides the same stage-histogram
            # machinery as execution stages, so SLO budgets keyed
            # 'tenant:<name>' work in _check_slo unchanged
            # (docs/SERVING.md "Tenants")
            tenant = freq.payload.get('tenant') or 'default'
            self._observe_stage(f'tenant:{tenant}', lat_ms)
            profiling.counter_inc('fleet.completed')
            freq.handle._fulfill(payload)
            return
        if is_terminal_error(payload):
            if freq.trace is not None and freq.sent_t is not None:
                freq.trace.span('wire.await', freq.sent_t, t_resp,
                                rid=rid, attempt=token,
                                error=type(payload).__name__)
            with self._lock:
                self._fail_locked(freq, payload)
            return
        self._attempt_failed(freq, rid, token, payload)

    def _observe_stage(self, stage: str, dur_ms: float) -> None:
        with self._lock:
            h = self._stage_h.get(stage)
            if h is None:
                h = self._stage_h[stage] = Histogram(
                    f'fleet.stage.{stage}_ms')
        h.observe(dur_ms)

    def _stitch(self, freq, rid, piggyback, t_resp: float) -> None:
        """Merge a completed attempt's replica-side spans into the
        router-side context, clock-aligned so cross-process stage
        ordering is monotone.

        The alignment: shift replica-clock times by the gossip-RTT
        clock offset (:class:`ClockOffsetEstimator`), falling back to
        centering the server-side window ``[mono_recv, mono_send]``
        inside the wire window when the estimate has no samples or
        lands the spans outside it; then clamp into the wire window —
        a uniform shift plus clamping preserves replica-side order and
        pins every replica span between ``wire.send`` and the response
        arrival, so the stitched waterfall is monotone by
        construction.  The ``wire.await`` span carries ``wire_ms``:
        the round trip minus the replica-observed window — pure
        wire + queueing cost of the hop."""
        ctx = freq.trace
        ws = freq.sent_t if freq.sent_t is not None else t_resp
        args = {'rid': rid, 'attempt': freq.attempts}
        spans = list(piggyback['spans'] or []) if piggyback else []
        if piggyback and piggyback.get('mono_recv') is not None:
            remote_win = max(
                0.0, piggyback['mono_send'] - piggyback['mono_recv'])
            args['wire_ms'] = round(
                max(0.0, (t_resp - ws) - remote_win) * 1e3, 3)
        ctx.span('wire.await', ws, t_resp, **args)
        self._observe_stage('wire.await', (t_resp - ws) * 1e3)
        if not spans:
            return
        with self._lock:
            est = self._clock.get(rid)
        delta = -est.offset if est is not None and est.n else None
        lo = min(s['t0'] for s in spans)
        hi = max(s['t1'] if s['t1'] is not None else s['t0']
                 for s in spans)
        if delta is None or not (ws <= lo + delta
                                 and hi + delta <= t_resp):
            mid_remote = None
            if piggyback.get('mono_recv') is not None:
                mid_remote = 0.5 * (piggyback['mono_recv']
                                    + piggyback['mono_send'])
            delta = 0.5 * (ws + t_resp) - (
                mid_remote if mid_remote is not None
                else 0.5 * (lo + hi))
        for s in spans:
            t0 = min(max(s['t0'] + delta, ws), t_resp)
            t1 = None if s['t1'] is None \
                else min(max(s['t1'] + delta, ws), t_resp)
            sargs = dict(s['args'])
            sargs['replica'] = rid
            ctx.spans.append({'name': s['name'], 't0': t0, 't1': t1,
                              'args': sargs})
            if s['t1'] is not None:
                # stage duration from the REPLICA's clock: offset
                # estimation error cancels inside one clock domain
                self._observe_stage(s['name'],
                                    (s['t1'] - s['t0']) * 1e3)

    def _fail_locked(self, freq, exc) -> None:
        if freq.done:
            return
        freq.done = True
        self._failed += 1
        profiling.counter_inc('fleet.failed')
        freq.handle._fail(exc)

    def _attempt_failed(self, freq, rid, token, exc) -> None:
        """One infrastructure-class attempt failure: breaker
        bookkeeping on the replica, then retry-or-exhaust under the
        fleet RetryPolicy."""
        t_fail = time.monotonic()
        with self._lock:
            if self._stale(freq, rid, token):
                return
            if freq.trace is not None and freq.sent_t is not None:
                freq.trace.span('wire.await', freq.sent_t, t_fail,
                                rid=rid, attempt=token,
                                error=type(exc).__name__)
                freq.sent_t = None
            if freq.first_error is None:
                freq.first_error = exc
            freq.excluded.add(rid)
            freq.rid = None
            freq.wire_id = None
            exhausted = freq.attempts >= self._retry_policy.max_attempts
            if exhausted:
                self._retry_exhausted += 1
                # exhaustion surfaces the ORIGINAL error, same rule as
                # the in-process retry path
                self._fail_locked(freq, freq.first_error)
            else:
                self._retries += 1
                if freq.trace is not None:
                    # the failover hop: this attempt died on `rid`,
                    # the retry pump will re-place it elsewhere
                    freq.trace.instant('failover', rid=rid,
                                       error=type(exc).__name__,
                                       attempt=token)
                self._park_locked(
                    freq, time.monotonic()
                    + self._retry_policy.delay_s(freq.attempts - 1))
        self._record_replica_failure(rid, exc)
        if exhausted:
            profiling.counter_inc('fleet.retry_exhausted')
        else:
            profiling.counter_inc('fleet.retries')
            self.flight_recorder.record(
                'fleet_retry', rid=rid, error=type(exc).__name__,
                attempt=token)

    def _record_replica_failure(self, rid, exc) -> None:
        trip = False
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            if rep.breaker.record_failure() and not rep.quarantined:
                rep.quarantined = True
                rep.breaker.trip(time.monotonic())
                self._breaker_trips += 1
                trip = True
        if trip:
            profiling.counter_inc('fleet.breaker_trips')
            self.flight_recorder.record(
                'fleet_breaker_trip', rid=rid,
                error=type(exc).__name__)

    def _replica_lost(self, rid, exc, via=None) -> None:
        """Connection death or gossip staleness: declare the replica
        down, recover every in-flight request it held, and retry each
        on a surviving replica.  ``via`` (a ReplicaClient) scopes the
        report to one specific connection — a replaced client's death
        must not take down its successor."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or not rep.alive \
                    or (via is not None and rep.client is not via):
                return
            rep.alive = False
            self._replica_down += 1
            recovered = list(rep.inflight.items())
            rep.inflight.clear()
            # re-home this replica's buckets on next placement
            for key in [k for k, r in self._home.items() if r == rid]:
                del self._home[key]
            self._failovers += len(recovered)
            client = rep.client
        profiling.counter_inc('fleet.replica_down')
        self.flight_recorder.record(
            'replica_down', rid=rid, reason=type(exc).__name__,
            recovered=len(recovered))
        # federated post-mortem: try to pull the victim's flight ring.
        # A SIGKILLed replica can't answer (the last gossiped digest
        # stands in); a WEDGED one answers after SIGCONT — async, so a
        # frozen socket never stalls the loss path
        if client is not None and client.alive:
            try:
                client.call_async(
                    'flight', {},
                    lambda ok, resp: self._on_flight_pull(
                        rid, ok, resp))
            except Exception:           # noqa: BLE001 - best effort
                pass
        for wire_id, (freq, token) in recovered:
            # a straggler response for this wire id must not complete
            # the handle after the retry lands elsewhere
            if client is not None:
                client.forget(wire_id)
            profiling.counter_inc('fleet.failover')
            self._attempt_failed(freq, rid, token, exc)

    # -- gossip ----------------------------------------------------------

    def _gossip_loop(self) -> None:
        while True:
            with self._lock:
                if self._closing:
                    return
                reps = list(self._replicas.values())
            for rep in reps:
                client = rep.client
                if client is None or not client.alive \
                        or rep.gossip_pending:
                    continue
                rep.gossip_pending = True
                t_send = time.monotonic()
                try:
                    client.call_async(
                        'gossip', {},
                        lambda ok, resp, rep=rep, t_send=t_send:
                        self._on_gossip(rep.rid, ok, resp, t_send))
                except ReplicaLostError:
                    rep.gossip_pending = False
            self._reconnect_dead(time.monotonic())
            self._check_staleness(time.monotonic())
            self._check_slo()
            with self._cv:
                if self._closing:
                    return
                self._cv.wait(self._gossip_interval_s)

    def _reconnect_dead(self, now: float) -> None:
        """Re-dial replicas whose TCP connection died while the
        process may have survived — e.g. a wire-corruption teardown
        (:class:`~.transport.WireCorruptionError` resets the
        connection by design) or a transient network blip.  Without
        this, a surviving replica whose socket dropped would stay
        delisted forever: the gossip revival path only helps replicas
        whose connection still works.  Throttled per replica to the
        liveness window; a process that is genuinely gone refuses the
        dial (swallowed — the fleet monitor respawns it with a fresh
        address and calls :meth:`add_replica` itself)."""
        targets = []
        with self._lock:
            if self._closing:
                return
            for rep in self._replicas.values():
                if rep.client is not None and not rep.client.alive \
                        and now - rep.reconnect_t \
                        >= self._liveness_window_s:
                    rep.reconnect_t = now
                    targets.append((rep.rid, rep.client.address))
        for rid, address in targets:
            try:
                self.add_replica(rid, address)
            except (OSError, ReplicaLostError):
                pass

    def _on_gossip(self, rid, ok, resp, t_send: float = None) -> None:
        t_recv = time.monotonic()
        recovered = readmitted = False
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            rep.gossip_pending = False
            if not ok:
                return
            rep.last_beat = time.monotonic()
            stats = resp.get('stats', resp)
            rep.digest = {
                'queue_depth': stats.get('queue_depth'),
                'est_wait_ms': stats.get('est_wait_ms'),
                'health': stats.get('health'),
                'completed': stats.get('completed'),
            }
            # clock probe: the heartbeat carried the replica's mono
            # clock; (t_send, mono, t_recv) is one NTP-style sample
            if t_send is not None and resp.get('mono') is not None:
                est = self._clock.get(rid)
                if est is None:
                    est = self._clock[rid] = ClockOffsetEstimator()
                est.add_sample(t_send, resp['mono'], t_recv)
            # flight digest: the newest ring tail this replica ever
            # gossiped — the post-mortem fallback when the process is
            # SIGKILLed and the ring can no longer be pulled
            fl = resp.get('flight')
            if fl is not None:
                self._flight_cache[rid] = {
                    'source': 'gossip', 'recorded': fl['recorded'],
                    'dropped': fl.get('dropped', 0),
                    'counts': fl['counts'], 'events': fl['tail'],
                    'mono': resp.get('mono'), 'cached_t': t_recv,
                }
            if not rep.alive:
                # a wedged replica resumed (SIGCONT): its connection
                # never died, its heartbeat just went stale; its
                # recovered requests completed elsewhere and their
                # wire callbacks were forgotten, so routing to it
                # again is safe
                rep.alive = True
                self._replica_up += 1
                recovered = True
            if rep.quarantined and rep.breaker.ready_to_probe(
                    time.monotonic()):
                rep.quarantined = False
                rep.breaker.readmit()
                self._readmissions += 1
                readmitted = True
        if recovered:
            profiling.counter_inc('fleet.replica_up')
            self.flight_recorder.record('replica_up', rid=rid,
                                        reason='heartbeat-recovered')
        if readmitted:
            profiling.counter_inc('fleet.readmissions')
            self.flight_recorder.record('fleet_readmit', rid=rid)

    def _check_staleness(self, now: float) -> None:
        stale = []
        with self._lock:
            for rep in self._replicas.values():
                if rep.alive and rep.client is not None \
                        and rep.client.alive \
                        and now - rep.last_beat \
                        > self._liveness_window_s:
                    stale.append(rep.rid)
        for rid in stale:
            self._gossip_stale += 1
            profiling.counter_inc('fleet.gossip_stale')
            self.flight_recorder.record('gossip_stale', rid=rid)
            self._replica_lost(rid, ReplicaLostError(
                f'{rid} heartbeat stale (> '
                f'{self._liveness_window_s * 1e3:.0f} ms)'))

    def _on_flight_pull(self, rid, ok, resp) -> None:
        if not ok or not isinstance(resp, dict):
            return
        with self._lock:
            self._flight_cache[rid] = {
                'source': 'pull', 'recorded': resp.get('recorded', 0),
                'dropped': resp.get('dropped', 0),
                'counts': resp.get('counts', {}),
                'events': resp.get('events', []),
                'mono': resp.get('mono'),
                'cached_t': time.monotonic(),
            }

    # -- SLO watch -------------------------------------------------------

    def _check_slo(self) -> None:
        """Evaluate live per-stage p50/p99 against the configured
        budgets (``slo_budgets={'execute': {'p99_ms': 50.0}, ...}``;
        stage ``'total'`` is submit→fulfil latency).  Breaches are
        edge-triggered: one ``slo_breach`` flight event + counter per
        excursion, not one per gossip tick."""
        if not self._slo_budgets:
            return
        breaches = []
        for stage, budget in self._slo_budgets.items():
            with self._lock:
                h = self._latency_h if stage == 'total' \
                    else self._stage_h.get(stage)
            if h is None or h.count < self._slo_min_samples:
                continue
            p50, p99 = h.percentile(50), h.percentile(99)
            bad = any(
                budget.get(k) is not None and p > budget[k]
                for k, p in (('p50_ms', p50), ('p99_ms', p99)))
            with self._lock:
                prev = self._slo_state.get(stage, False)
                self._slo_state[stage] = bad
                self._slo_last[stage] = {
                    'p50_ms': round(p50, 3), 'p99_ms': round(p99, 3),
                    'breached': bad, 'samples': h.count}
                if bad and not prev:
                    self._slo_breaches += 1
                    breaches.append((stage, p50, p99, budget))
        for stage, p50, p99, budget in breaches:
            profiling.counter_inc('fleet.slo_breach')
            self.flight_recorder.record(
                'slo_breach', stage=stage, p50_ms=round(p50, 3),
                p99_ms=round(p99, 3), budget=dict(budget))

    def slo_breached(self) -> bool:
        """True while ANY configured SLO budget (fleet-wide stage or
        per-tenant ``'tenant:<name>'``) is currently breached — the
        level signal the fleet autoscaler integrates over time
        (docs/FLEET.md "Autoscaling"); the flight events stay
        edge-triggered."""
        with self._lock:
            return any(self._slo_state.values())

    # -- fleet observability (docs/OBSERVABILITY.md) ---------------------

    def set_trace_sample(self, sample: float) -> None:
        """Retune request-trace sampling live (bench sweeps and chaos
        tooling); retained contexts survive the change."""
        self._tracer.set_sample(sample)

    def trace_contexts(self) -> list:
        """Retained stitched trace contexts, oldest first."""
        return self._tracer.contexts()

    def dump_trace(self, path: str) -> int:
        """Export the stitched fleet trace (router spans + clock-
        aligned replica spans, one ``tid`` row per sampled request) as
        Chrome Trace JSON; returns the event count."""
        return write_chrome_trace(path, self._tracer.contexts(),
                                  pid=f'fleet-{self.name}')

    def clock_offsets(self) -> dict:
        """Per-replica estimated clock offset (``replica - router``
        seconds) and its worst-case error bound."""
        with self._lock:
            ests = dict(self._clock)
        return {rid: {'offset_s': est.offset,
                      'uncertainty_s': est.uncertainty_s,
                      'samples': est.n}
                for rid, est in sorted(ests.items()) if est.n}

    def fleet_metrics(self, timeout_s: float = 10.0) -> dict:
        """Pull every reachable replica's metrics-registry snapshot
        (the ``fleet-metrics`` wire op); unreachable replicas are
        silently absent — this is an observability read, never a
        liveness judgement."""
        out = {}
        for rid in self.replica_ids():
            try:
                resp = self.call_replica(rid, 'fleet-metrics',
                                         timeout_s=timeout_s)
                out[rid] = resp['metrics']
            except Exception:           # noqa: BLE001 - best effort
                continue
        return out

    def prometheus_text(self, timeout_s: float = 10.0) -> str:
        """One pane of glass: every replica's ``serve.*`` /
        ``compile_cache.*`` metric re-exposed with a ``replica`` label
        plus fleet-level rollups (summed counters, merged histograms),
        followed by the router's own first-class fleet metrics —
        routable count, per-replica gossip staleness and clock offset,
        failover/park/SLO counters, per-stage latency histograms."""
        lines = merged_prometheus_text(self.fleet_metrics(timeout_s),
                                       label='replica')
        lines.extend(self._fleet_prom_lines())
        return '\n'.join(lines) + ('\n' if lines else '')

    def _fleet_prom_lines(self) -> list:
        from ..obs.metrics import _format_labels
        with self._lock:
            now = time.monotonic()
            counters = {
                'fleet.submitted': self._submitted,
                'fleet.completed': self._completed,
                'fleet.failed': self._failed,
                'fleet.retries': self._retries,
                'fleet.retry_exhausted': self._retry_exhausted,
                'fleet.failovers': self._failovers,
                'fleet.replica_down': self._replica_down,
                'fleet.replica_up': self._replica_up,
                'fleet.gossip_stale': self._gossip_stale,
                'fleet.breaker_trips': self._breaker_trips,
                'fleet.readmissions': self._readmissions,
                'fleet.slo_breaches': self._slo_breaches,
            }
            gauges = {
                'fleet.n_replicas': float(len(self._replicas)),
                'fleet.n_routable': float(sum(
                    1 for r in self._replicas.values()
                    if r.routable())),
                'fleet.parked': float(len(self._pending)),
            }
            beats = {rid: (now - rep.last_beat) * 1e3
                     for rid, rep in sorted(self._replicas.items())}
            offsets = {rid: est.offset * 1e3
                       for rid, est in sorted(self._clock.items())
                       if est.n}
            hists = {h.name: h.state()
                     for h in self._stage_h.values()}
            hists[self._latency_h.name] = self._latency_h.state()
        lines = prometheus_snapshot_lines(
            {'counters': counters, 'gauges': gauges,
             'histograms': hists})
        lines.append('# TYPE fleet_heartbeat_age_ms gauge')
        for rid, age in beats.items():
            lines.append(
                'fleet_heartbeat_age_ms'
                f'{_format_labels({"replica": rid})} {round(age, 3)}')
        if offsets:
            lines.append('# TYPE fleet_clock_offset_ms gauge')
            for rid, off in offsets.items():
                lines.append(
                    'fleet_clock_offset_ms'
                    f'{_format_labels({"replica": rid})} '
                    f'{round(off, 3)}')
        return lines

    def merged_flight(self, pull: bool = True,
                      timeout_s: float = 2.0) -> dict:
        """The federated incident timeline: the router's own ring plus
        every replica's (live-pulled when reachable, else the last
        gossiped digest), each event time-aligned onto the router's
        clock via the gossip-RTT offset and merged into one ordered
        stream.  Events carry ``origin`` (``router`` or the replica
        id) and ``t_router`` (aligned monotonic seconds)."""
        if pull:
            for rid in self.replica_ids():
                try:
                    resp = self.call_replica(rid, 'flight',
                                             timeout_s=timeout_s)
                    self._on_flight_pull(rid, True, resp)
                except Exception:       # noqa: BLE001 - cache stands
                    continue
        with self._lock:
            cache = {rid: dict(c)
                     for rid, c in self._flight_cache.items()}
            offsets = {rid: est.offset
                       for rid, est in self._clock.items() if est.n}
        merged = []
        for ev in self.flight_recorder.events():
            e = dict(ev)
            e['origin'] = 'router'
            e['t_router'] = ev.get('mono')
            merged.append(e)
        for rid, c in sorted(cache.items()):
            off = offsets.get(rid)
            for ev in c['events']:
                e = dict(ev)
                e['origin'] = rid
                m = ev.get('mono')
                e['t_router'] = None if m is None \
                    else (m - off if off is not None else m)
                merged.append(e)
        merged.sort(key=lambda e: (e['t_router'] is None,
                                   e['t_router'] or 0.0))
        return {
            'router': {'recorded': self.flight_recorder.recorded,
                       'dropped': self.flight_recorder.dropped,
                       'counts': self.flight_recorder.counts()},
            'replicas': {rid: {k: c.get(k) for k in
                               ('source', 'recorded', 'dropped',
                                'counts')}
                         for rid, c in sorted(cache.items())},
            'clock_offsets': self.clock_offsets(),
            'events': merged,
        }

    # -- retry pump ------------------------------------------------------

    def _retry_loop(self) -> None:
        while True:
            with self._cv:
                if self._closing:
                    return
                now = time.monotonic()
                if not self._pending:
                    self._cv.wait(0.1)
                    continue
                eligible_t, _seq, freq = self._pending[0]
                if eligible_t > now:
                    self._cv.wait(min(eligible_t - now, 0.1))
                    continue
                heapq.heappop(self._pending)
            self._dispatch(freq)

    # -- introspection / shutdown ---------------------------------------

    def stats(self) -> dict:
        with self._lock:
            now = time.monotonic()
            replicas = {
                rid: {
                    'alive': rep.alive,
                    'quarantined': rep.quarantined,
                    'routable': rep.routable(),
                    'heartbeat_age_ms': (now - rep.last_beat) * 1e3,
                    'inflight': len(rep.inflight),
                    'breaker': rep.breaker.snapshot(),
                    'digest': dict(rep.digest),
                } for rid, rep in sorted(self._replicas.items())}
            snap = {
                'replicas': replicas,
                'n_replicas': len(self._replicas),
                'n_routable': sum(1 for r in self._replicas.values()
                                  if r.routable()),
                'submitted': self._submitted,
                'completed': self._completed,
                'failed': self._failed,
                'parked': len(self._pending),
                'retries': self._retries,
                'retry_exhausted': self._retry_exhausted,
                'failovers': self._failovers,
                'replica_down': self._replica_down,
                'replica_up': self._replica_up,
                'gossip_stale': self._gossip_stale,
                'breaker_trips': self._breaker_trips,
                'readmissions': self._readmissions,
                'home_buckets': len(self._home),
                'streaming': {
                    'open_sessions': len(self._stream_sessions),
                    'rounds_submitted': self._stream_rounds,
                },
                'slo_breaches': self._slo_breaches,
                'slo': {stage: dict(ev)
                        for stage, ev in sorted(self._slo_last.items())},
            }
        lat = np.asarray(self._latency_h.values(), np.float64)
        if lat.size:
            snap['latency_p50_ms'] = float(np.percentile(lat, 50))
            snap['latency_p99_ms'] = float(np.percentile(lat, 99))
        else:
            snap['latency_p50_ms'] = snap['latency_p99_ms'] = 0.0
        snap['latency_samples'] = int(lat.size)
        reg = profiling.registry()
        reg.set_gauge(f'fleet.{self.name}.n_routable',
                      snap['n_routable'])
        reg.set_gauge(f'fleet.{self.name}.parked', snap['parked'])
        return snap

    def shutdown(self) -> None:
        """Stop routing: fail every parked and in-flight request with
        :class:`ShutdownError`, close every client, join the gossip and
        retry threads.  Idempotent."""
        with self._cv:
            already = self._closing
            self._closing = True
            self._cv.notify_all()
        self._join_threads()
        if already:
            return
        with self._lock:
            doomed = [f for _, _, f in self._pending]
            self._pending.clear()
            for rep in self._replicas.values():
                doomed.extend(f for f, _tok in rep.inflight.values())
                rep.inflight.clear()
            clients = [rep.client for rep in self._replicas.values()
                       if rep.client is not None]
        err = ShutdownError(f'fleet router {self.name!r} shut down')
        with self._lock:
            for freq in doomed:
                self._fail_locked(freq, err)
        for client in clients:
            client.close()

    def _join_threads(self) -> None:
        for t in (self._gossip_thread, self._retry_thread):
            if t is not threading.current_thread():
                t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.shutdown()
