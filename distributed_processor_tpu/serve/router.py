"""FleetRouter: the front door that load-balances across replicas.

One router process fans ``submit`` / ``submit_source`` traffic out over
N :class:`~.service.ExecutionService` replicas (separate processes,
reached through :mod:`.transport`), and keeps serving — bit-identical
or typed — while replicas die, hang, or restart (docs/FLEET.md).  The
design deliberately re-uses the in-process supervision vocabulary one
ring out:

* **Health gossip.**  A gossip thread polls every replica's ``stats()``
  digest (queue depth, est_wait, health mix) on ``gossip_interval_ms``.
  Each response re-arms the replica's heartbeat; a replica whose last
  heartbeat exceeds ``liveness_window_ms`` is declared down
  (``gossip_stale`` + ``replica_down`` flight events) even when its TCP
  connection still accepts bytes — the wedged-process case a connection
  error can never surface.  A stale replica that beats again (a SIGCONT
  after a wedge) is simply re-admitted: its recovered requests already
  completed elsewhere, and the stale wire callbacks were forgotten, so
  resuming routing to it is safe.
* **Fleet-level circuit breakers.**  Each replica carries a
  :class:`~.supervise.CircuitBreaker`; consecutive infrastructure
  failures attributed to it (connection loss, ``OverloadError``, chaos
  crashes) quarantine it for the breaker cooldown, and the first
  heartbeat after cooldown re-admits it.
* **Cross-replica retry.**  In-flight requests on a dead replica are
  recovered from the router's shadow ledger and re-dispatched to a
  surviving replica under the shared :class:`~.supervise.RetryPolicy`:
  attempts are bounded, backoff is exponential, exhaustion surfaces the
  ORIGINAL infrastructure error.  Typed program-class errors
  (``FaultError``, validation — :func:`is_infrastructure_error`) and
  terminal request outcomes (``DeadlineError``, ``CancelledError`` /
  ``ShutdownError``) are NEVER retried.  Every dispatch carries an
  attempt token (mirroring :class:`~.request.RequestHandle`'s claim
  tokens): a straggling response or failure report whose token went
  stale is a silent no-op, so a request can never be double-completed
  or double-retried no matter how wire callbacks interleave.
* **Bucket affinity.**  Placement is sticky per
  :class:`~.bucketspec.BucketSpec` coalescing template: a bucket's home
  replica keeps its jit/AOT caches hot, exactly like the per-device
  sticky-bucket map inside the service; ties break to the least-loaded
  live replica by gossiped est_wait / queue depth.

The router owns no execution and no devices — it is restartable state:
everything here rebuilds from replicas' gossip within one interval.
"""

from __future__ import annotations

import collections
import heapq
import threading
import time

import numpy as np

from ..sim.interpreter import is_infrastructure_error
from ..utils import profiling
from ..obs import FlightRecorder, Histogram
from .. import isa
from .batcher import bucket_key
from .request import (CancelledError, DeadlineError, RequestHandle,
                      ServiceClosedError, ShutdownError)
from .supervise import CircuitBreaker, RetryPolicy
from .transport import ReplicaClient, ReplicaLostError

ROUTER_THREAD_PREFIX = 'dproc-serve-fleet'


def is_terminal_error(exc: BaseException) -> bool:
    """True when a failed attempt must surface to the caller instead of
    retrying on another replica: program-class errors reproduce
    anywhere (:func:`is_infrastructure_error` False), and expired
    deadlines / cancellations are properties of the REQUEST's clock —
    infrastructure-class by taxonomy, but re-execution cannot
    un-expire them."""
    return (not is_infrastructure_error(exc)
            or isinstance(exc, (DeadlineError, CancelledError)))


class _FleetRequest:
    """Router-side shadow of one submission: everything needed to
    re-dispatch it on another replica (the full payload), plus the
    retry ledger.  ``attempts`` doubles as the attempt token — each
    dispatch bumps it, and response/failure handlers that present a
    stale ``(rid, token)`` pair are dropped."""

    __slots__ = ('handle', 'op', 'payload', 'key', 'attempts',
                 'first_error', 'excluded', 'submit_t', 'rid',
                 'wire_id', 'done')

    def __init__(self, op, payload, key):
        self.handle = RequestHandle()
        self.op = op
        self.payload = payload
        self.key = key
        self.attempts = 0           # executions started == token
        self.first_error = None     # original infra error, kept for
        self.excluded = set()       # exhaustion (RetryPolicy rule)
        self.submit_t = time.monotonic()
        self.rid = None             # replica of the CURRENT attempt
        self.wire_id = None
        self.done = False


class _Replica:
    __slots__ = ('rid', 'client', 'breaker', 'alive', 'quarantined',
                 'last_beat', 'digest', 'inflight', 'gossip_pending')

    def __init__(self, rid, client, breaker):
        self.rid = rid
        self.client = client
        self.breaker = breaker
        self.alive = True
        self.quarantined = False
        self.last_beat = time.monotonic()
        self.digest = {}
        self.inflight = {}          # wire_id -> (_FleetRequest, token)
        self.gossip_pending = False

    def routable(self) -> bool:
        return self.alive and not self.quarantined \
            and self.client is not None and self.client.alive

    def load(self) -> tuple:
        # gossiped load: est_wait (None sorts as 0) then queue depth
        ew = self.digest.get('est_wait_ms') or 0.0
        return (float(ew), int(self.digest.get('queue_depth') or 0),
                len(self.inflight))


class FleetRouter:
    """Load-balancing, self-healing front door over replica clients.

    Replicas register through :meth:`add_replica` (the
    :class:`~.fleet.Fleet` process manager calls it at spawn and
    respawn); ``submit``/``submit_source`` mirror the service's
    signatures and return local :class:`RequestHandle`\\ s fulfilled
    from wire responses.  ``shutdown`` fails everything still pending
    with :class:`ShutdownError` — after it returns no handle can block
    forever, same contract as the service.
    """

    def __init__(self, *, default_cfg=None, retry_policy=None,
                 gossip_interval_ms: float = 25.0,
                 liveness_window_ms: float = 250.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_ms: float = 500.0,
                 name: str = None, flight_events: int = 512):
        if liveness_window_ms <= gossip_interval_ms:
            raise ValueError('liveness window must exceed the gossip '
                             'interval (one missed beat is not death)')
        self.name = name or 'fleet'
        self._default_cfg = default_cfg
        self._retry_policy = retry_policy or RetryPolicy()
        self._gossip_interval_s = gossip_interval_ms / 1e3
        self._liveness_window_s = liveness_window_ms / 1e3
        self._breaker_threshold = breaker_threshold
        self._breaker_cooldown_s = breaker_cooldown_ms / 1e3
        self.flight_recorder = FlightRecorder(flight_events)
        self._latency_h = Histogram('fleet.latency_ms')
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._replicas: dict = {}       # rid -> _Replica
        self._home: dict = {}           # bucket identity -> rid
        self._pending: list = []        # heap of (eligible_t, seq, freq)
        self._pending_seq = 0
        self._closing = False
        # counters (written under _lock)
        self._submitted = 0
        self._completed = 0
        self._failed = 0
        self._retries = 0
        self._retry_exhausted = 0
        self._failovers = 0             # requests recovered off a dead
        self._replica_down = 0          # replica and re-queued
        self._replica_up = 0
        self._gossip_stale = 0
        self._breaker_trips = 0
        self._readmissions = 0
        self._gossip_thread = threading.Thread(
            target=self._gossip_loop,
            name=f'{ROUTER_THREAD_PREFIX}-gossip-{self.name}',
            daemon=True)
        self._retry_thread = threading.Thread(
            target=self._retry_loop,
            name=f'{ROUTER_THREAD_PREFIX}-retry-{self.name}',
            daemon=True)
        self._gossip_thread.start()
        self._retry_thread.start()

    # -- replica membership ---------------------------------------------

    def add_replica(self, rid: str, address) -> None:
        """Connect to (or reconnect to a respawned) replica at
        ``address`` and start routing to it."""
        client = ReplicaClient(
            address,
            # late-bound `client`: the loss guard must name the exact
            # connection that died, so a replaced client's death can
            # never take down its successor
            on_lost=lambda exc: self._replica_lost(rid, exc,
                                                   via=client))
        with self._lock:
            old = self._replicas.get(rid)
        if old is not None and old.alive:
            # replacing a live replica: fail its in-flight work over
            # first so nothing is silently dropped
            self._replica_lost(rid, ReplicaLostError(f'{rid} replaced'))
        with self._lock:
            old = self._replicas.get(rid)
            self._replicas[rid] = _Replica(
                rid, client,
                CircuitBreaker(self._breaker_threshold,
                               self._breaker_cooldown_s))
            self._replica_up += 1
            self._cv.notify_all()
        if old is not None and old.client is not None:
            old.client.close()
        profiling.counter_inc('fleet.replica_up')
        self.flight_recorder.record('replica_up', rid=rid,
                                    address=list(address))

    def remove_replica(self, rid: str) -> None:
        """Forget a replica (fleet scale-down): in-flight work fails
        over exactly as if it died."""
        self._replica_lost(rid, ReplicaLostError(f'{rid} removed'))
        with self._lock:
            rep = self._replicas.pop(rid, None)
        if rep is not None and rep.client is not None:
            rep.client.close()

    def replica_ids(self) -> list:
        with self._lock:
            return sorted(self._replicas)

    def primary_replica(self):
        """The routable replica carrying the most load right now
        (in-flight wire requests, then gossiped queue depth, then home
        buckets) — chaos tooling kills THIS one so the fault always
        lands on the serving path, even when bucket affinity has
        pinned a single-bucket workload to one home."""
        with self._lock:
            homes = collections.Counter(self._home.values())
            live = [r for r in self._replicas.values()
                    if r.routable()]
            if not live:
                return None
            best = max(live, key=lambda r: (
                len(r.inflight),
                int(r.digest.get('queue_depth') or 0),
                homes[r.rid], r.rid))
            return best.rid

    def call_replica(self, rid: str, op: str = 'stats', payload=None,
                     timeout_s: float = 30.0):
        """Synchronous wire call to ONE specific replica (fleet tests
        and tooling inspect individual replicas this way — e.g. the
        warmed-respawn assertion reads the new replica's compile
        counters directly)."""
        with self._lock:
            rep = self._replicas.get(rid)
            client = rep.client if rep is not None else None
        if client is None:
            raise KeyError(f'unknown replica {rid!r}')
        return client.call(op, payload or {}, timeout_s=timeout_s)

    # -- submission ------------------------------------------------------

    def submit(self, mp, meas_bits=None, *, shots: int = None,
               init_regs=None, cfg=None, priority: int = 0,
               deadline_ms: float = None,
               fault_mode: str = None) -> RequestHandle:
        payload = dict(mp=mp, meas_bits=meas_bits, shots=shots,
                       init_regs=init_regs,
                       cfg=cfg if cfg is not None else self._default_cfg,
                       priority=priority, deadline_ms=deadline_ms,
                       fault_mode=fault_mode)
        return self._enqueue('submit', payload,
                             self._affinity_key(mp, payload['cfg']))

    def submit_source(self, program, qchip, *, shots: int = None,
                      meas_bits=None, init_regs=None, cfg=None,
                      priority: int = 0, deadline_ms: float = None,
                      fault_mode: str = None, n_qubits: int = 8,
                      pad_to: int = None) -> RequestHandle:
        payload = dict(program=program, qchip=qchip, shots=shots,
                       meas_bits=meas_bits, init_regs=init_regs,
                       cfg=cfg if cfg is not None else self._default_cfg,
                       priority=priority, deadline_ms=deadline_ms,
                       fault_mode=fault_mode, n_qubits=n_qubits,
                       pad_to=pad_to)
        # no machine program yet, so no bucket: least-loaded placement
        return self._enqueue('submit_source', payload, None)

    def _affinity_key(self, mp, cfg):
        """The bucket-affinity identity: the same unbound BucketSpec
        template the replica's coalescer will key on.  Any failure to
        compute it (odd cfg, validation the replica will surface typed)
        degrades to least-loaded placement, never an error."""
        try:
            from .service import _normalize_cfg
            ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mp.n_instr))
            return bucket_key(mp, ncfg).identity()
        except Exception:               # noqa: BLE001
            return None

    def _enqueue(self, op, payload, key) -> RequestHandle:
        freq = _FleetRequest(op, payload, key)
        with self._lock:
            if self._closing:
                raise ServiceClosedError(
                    f'fleet router {self.name!r} is shut down')
            self._submitted += 1
        profiling.counter_inc('fleet.submitted')
        self._dispatch(freq)
        return freq.handle

    # -- placement / dispatch -------------------------------------------

    def _place_locked(self, freq):
        live = [r for r in self._replicas.values() if r.routable()]
        candidates = [r for r in live if r.rid not in freq.excluded] \
            or live                     # all excluded: any live one
        if not candidates:
            return None
        if freq.key is not None:
            home = self._home.get(freq.key)
            for r in candidates:
                if r.rid == home:
                    return r
        best = min(candidates, key=lambda r: (r.load(), r.rid))
        if freq.key is not None:
            self._home[freq.key] = best.rid
        return best

    def _dispatch(self, freq) -> None:
        """Place and send one request; parks it (the retry pump re-tries
        placement) when no replica is routable right now."""
        with self._lock:
            if freq.done:
                return
            if self._closing:
                self._fail_locked(freq, ShutdownError(
                    f'fleet router {self.name!r} is shut down'))
                return
            rep = self._place_locked(freq)
            if rep is None:
                self._park_locked(freq, time.monotonic() + 0.02)
                return
            freq.attempts += 1
            token = freq.attempts
            freq.rid = rep.rid
            freq.wire_id = None
            client = rep.client
        try:
            wire_id = client.call_async(
                freq.op, freq.payload,
                lambda ok, resp: self._on_response(
                    freq, rep.rid, token, ok, resp))
        except ReplicaLostError as exc:
            # the send failed (the client's loss path may have already
            # routed this attempt through _on_response — the token
            # guard makes this call a no-op in that case)
            self._attempt_failed(freq, rep.rid, token, exc)
            return
        with self._lock:
            r = self._replicas.get(rep.rid)
            if (not freq.done and freq.attempts == token
                    and freq.rid == rep.rid
                    and r is not None and r.client is client):
                freq.wire_id = wire_id
                r.inflight[wire_id] = (freq, token)

    def _park_locked(self, freq, eligible_t: float) -> None:
        self._pending_seq += 1
        heapq.heappush(self._pending,
                       (eligible_t, self._pending_seq, freq))
        self._cv.notify_all()

    # -- responses / failures -------------------------------------------

    def _stale(self, freq, rid, token) -> bool:
        # caller holds _lock: a report about attempt `token` on `rid`
        # is stale once the request completed, moved on to another
        # attempt, or was already failed-over off this replica
        return freq.done or freq.attempts != token or freq.rid != rid

    def _on_response(self, freq, rid, token, ok, payload) -> None:
        with self._lock:
            if self._stale(freq, rid, token):
                return
            rep = self._replicas.get(rid)
            if rep is not None and freq.wire_id is not None:
                rep.inflight.pop(freq.wire_id, None)
            if ok:
                freq.done = True
                self._completed += 1
                if rep is not None:
                    rep.breaker.record_success()
                lat_ms = (time.monotonic() - freq.submit_t) * 1e3
        if ok:
            self._latency_h.observe(lat_ms)
            profiling.counter_inc('fleet.completed')
            freq.handle._fulfill(payload)
            return
        if is_terminal_error(payload):
            with self._lock:
                self._fail_locked(freq, payload)
            return
        self._attempt_failed(freq, rid, token, payload)

    def _fail_locked(self, freq, exc) -> None:
        if freq.done:
            return
        freq.done = True
        self._failed += 1
        profiling.counter_inc('fleet.failed')
        freq.handle._fail(exc)

    def _attempt_failed(self, freq, rid, token, exc) -> None:
        """One infrastructure-class attempt failure: breaker
        bookkeeping on the replica, then retry-or-exhaust under the
        fleet RetryPolicy."""
        with self._lock:
            if self._stale(freq, rid, token):
                return
            if freq.first_error is None:
                freq.first_error = exc
            freq.excluded.add(rid)
            freq.rid = None
            freq.wire_id = None
            exhausted = freq.attempts >= self._retry_policy.max_attempts
            if exhausted:
                self._retry_exhausted += 1
                # exhaustion surfaces the ORIGINAL error, same rule as
                # the in-process retry path
                self._fail_locked(freq, freq.first_error)
            else:
                self._retries += 1
                self._park_locked(
                    freq, time.monotonic()
                    + self._retry_policy.delay_s(freq.attempts - 1))
        self._record_replica_failure(rid, exc)
        if exhausted:
            profiling.counter_inc('fleet.retry_exhausted')
        else:
            profiling.counter_inc('fleet.retries')
            self.flight_recorder.record(
                'fleet_retry', rid=rid, error=type(exc).__name__,
                attempt=token)

    def _record_replica_failure(self, rid, exc) -> None:
        trip = False
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            if rep.breaker.record_failure() and not rep.quarantined:
                rep.quarantined = True
                rep.breaker.trip(time.monotonic())
                self._breaker_trips += 1
                trip = True
        if trip:
            profiling.counter_inc('fleet.breaker_trips')
            self.flight_recorder.record(
                'fleet_breaker_trip', rid=rid,
                error=type(exc).__name__)

    def _replica_lost(self, rid, exc, via=None) -> None:
        """Connection death or gossip staleness: declare the replica
        down, recover every in-flight request it held, and retry each
        on a surviving replica.  ``via`` (a ReplicaClient) scopes the
        report to one specific connection — a replaced client's death
        must not take down its successor."""
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None or not rep.alive \
                    or (via is not None and rep.client is not via):
                return
            rep.alive = False
            self._replica_down += 1
            recovered = list(rep.inflight.items())
            rep.inflight.clear()
            # re-home this replica's buckets on next placement
            for key in [k for k, r in self._home.items() if r == rid]:
                del self._home[key]
            self._failovers += len(recovered)
            client = rep.client
        profiling.counter_inc('fleet.replica_down')
        self.flight_recorder.record(
            'replica_down', rid=rid, reason=type(exc).__name__,
            recovered=len(recovered))
        for wire_id, (freq, token) in recovered:
            # a straggler response for this wire id must not complete
            # the handle after the retry lands elsewhere
            if client is not None:
                client.forget(wire_id)
            profiling.counter_inc('fleet.failover')
            self._attempt_failed(freq, rid, token, exc)

    # -- gossip ----------------------------------------------------------

    def _gossip_loop(self) -> None:
        while True:
            with self._lock:
                if self._closing:
                    return
                reps = list(self._replicas.values())
            for rep in reps:
                client = rep.client
                if client is None or not client.alive \
                        or rep.gossip_pending:
                    continue
                rep.gossip_pending = True
                try:
                    client.call_async(
                        'stats', {},
                        lambda ok, resp, rep=rep: self._on_gossip(
                            rep.rid, ok, resp))
                except ReplicaLostError:
                    rep.gossip_pending = False
            self._check_staleness(time.monotonic())
            with self._cv:
                if self._closing:
                    return
                self._cv.wait(self._gossip_interval_s)

    def _on_gossip(self, rid, ok, resp) -> None:
        recovered = readmitted = False
        with self._lock:
            rep = self._replicas.get(rid)
            if rep is None:
                return
            rep.gossip_pending = False
            if not ok:
                return
            rep.last_beat = time.monotonic()
            rep.digest = {
                'queue_depth': resp.get('queue_depth'),
                'est_wait_ms': resp.get('est_wait_ms'),
                'health': resp.get('health'),
                'completed': resp.get('completed'),
            }
            if not rep.alive:
                # a wedged replica resumed (SIGCONT): its connection
                # never died, its heartbeat just went stale; its
                # recovered requests completed elsewhere and their
                # wire callbacks were forgotten, so routing to it
                # again is safe
                rep.alive = True
                self._replica_up += 1
                recovered = True
            if rep.quarantined and rep.breaker.ready_to_probe(
                    time.monotonic()):
                rep.quarantined = False
                rep.breaker.readmit()
                self._readmissions += 1
                readmitted = True
        if recovered:
            profiling.counter_inc('fleet.replica_up')
            self.flight_recorder.record('replica_up', rid=rid,
                                        reason='heartbeat-recovered')
        if readmitted:
            profiling.counter_inc('fleet.readmissions')
            self.flight_recorder.record('fleet_readmit', rid=rid)

    def _check_staleness(self, now: float) -> None:
        stale = []
        with self._lock:
            for rep in self._replicas.values():
                if rep.alive and rep.client is not None \
                        and rep.client.alive \
                        and now - rep.last_beat \
                        > self._liveness_window_s:
                    stale.append(rep.rid)
        for rid in stale:
            self._gossip_stale += 1
            profiling.counter_inc('fleet.gossip_stale')
            self.flight_recorder.record('gossip_stale', rid=rid)
            self._replica_lost(rid, ReplicaLostError(
                f'{rid} heartbeat stale (> '
                f'{self._liveness_window_s * 1e3:.0f} ms)'))

    # -- retry pump ------------------------------------------------------

    def _retry_loop(self) -> None:
        while True:
            with self._cv:
                if self._closing:
                    return
                now = time.monotonic()
                if not self._pending:
                    self._cv.wait(0.1)
                    continue
                eligible_t, _seq, freq = self._pending[0]
                if eligible_t > now:
                    self._cv.wait(min(eligible_t - now, 0.1))
                    continue
                heapq.heappop(self._pending)
            self._dispatch(freq)

    # -- introspection / shutdown ---------------------------------------

    def stats(self) -> dict:
        with self._lock:
            now = time.monotonic()
            replicas = {
                rid: {
                    'alive': rep.alive,
                    'quarantined': rep.quarantined,
                    'routable': rep.routable(),
                    'heartbeat_age_ms': (now - rep.last_beat) * 1e3,
                    'inflight': len(rep.inflight),
                    'breaker': rep.breaker.snapshot(),
                    'digest': dict(rep.digest),
                } for rid, rep in sorted(self._replicas.items())}
            snap = {
                'replicas': replicas,
                'n_replicas': len(self._replicas),
                'n_routable': sum(1 for r in self._replicas.values()
                                  if r.routable()),
                'submitted': self._submitted,
                'completed': self._completed,
                'failed': self._failed,
                'parked': len(self._pending),
                'retries': self._retries,
                'retry_exhausted': self._retry_exhausted,
                'failovers': self._failovers,
                'replica_down': self._replica_down,
                'replica_up': self._replica_up,
                'gossip_stale': self._gossip_stale,
                'breaker_trips': self._breaker_trips,
                'readmissions': self._readmissions,
                'home_buckets': len(self._home),
            }
        lat = np.asarray(self._latency_h.values(), np.float64)
        if lat.size:
            snap['latency_p50_ms'] = float(np.percentile(lat, 50))
            snap['latency_p99_ms'] = float(np.percentile(lat, 99))
        else:
            snap['latency_p50_ms'] = snap['latency_p99_ms'] = 0.0
        snap['latency_samples'] = int(lat.size)
        reg = profiling.registry()
        reg.set_gauge(f'fleet.{self.name}.n_routable',
                      snap['n_routable'])
        reg.set_gauge(f'fleet.{self.name}.parked', snap['parked'])
        return snap

    def shutdown(self) -> None:
        """Stop routing: fail every parked and in-flight request with
        :class:`ShutdownError`, close every client, join the gossip and
        retry threads.  Idempotent."""
        with self._cv:
            already = self._closing
            self._closing = True
            self._cv.notify_all()
        self._join_threads()
        if already:
            return
        with self._lock:
            doomed = [f for _, _, f in self._pending]
            self._pending.clear()
            for rep in self._replicas.values():
                doomed.extend(f for f, _tok in rep.inflight.values())
                rep.inflight.clear()
            clients = [rep.client for rep in self._replicas.values()
                       if rep.client is not None]
        err = ShutdownError(f'fleet router {self.name!r} shut down')
        with self._lock:
            for freq in doomed:
                self._fail_locked(freq, err)
        for client in clients:
            client.close()

    def _join_threads(self) -> None:
        for t in (self._gossip_thread, self._retry_thread):
            if t is not threading.current_thread():
                t.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        self.shutdown()
