"""Continuous-batching benchmark: the service's reason to exist, timed.

One workload, two executions: ``n_reqs`` independent single-program
requests (distinct random RB sequences — the realistic many-users
shape) run (a) sequentially, one ``simulate_batch`` dispatch per
program, and (b) through :class:`~.service.ExecutionService`, which
coalesces them into shape-bucketed multi-program dispatches.  Both
sides use the same normalized generic-engine cfg and both rounds are
timed WARM (a cold round runs first to pay the one-per-bucket
compile), so the ratio isolates the dispatch economics: N host
round-trips vs ~1.  Results are asserted bit-identical before any
number is reported.

Two further modes probe the multi-device pool:

* :func:`multi_device_scaling` — the pod-scale headline: the same
  closed-loop workload at dp=1/2/... per-device executors, warm,
  bit-identity asserted per request before any timing, per-device
  traffic recorded from ``stats()``.
* :func:`open_loop_latency` — p50/p99 request latency under a seeded
  Poisson-ish MIXED-bucket arrival process (open loop: arrivals do not
  wait for completions, so queueing delay is measured honestly instead
  of being hidden by submit backpressure).

Shared by the ``continuous_batching`` / ``serve_open_loop`` rows in
bench.py and the ``serve-bench`` CLI subcommand.  ``python -m
distributed_processor_tpu.serve.benchmark scaling|openloop ...`` runs
either mode standalone — bench.py uses that to force a multi-device
CPU host (``--xla_force_host_platform_device_count``) in a subprocess
when the parent process sees too few devices.
"""

from __future__ import annotations

import os
import tempfile
import time

import numpy as np

import jax

from .. import isa
from ..models import active_reset, make_default_qchip, rb_ensemble
from ..pipeline import compile_to_machine
from ..sim.interpreter import (InterpreterConfig, multi_trace_count,
                               simulate_batch)
from .bucketspec import BucketSpec
from .catalog import BucketCatalog
from .service import ExecutionService, _normalize_cfg


def continuous_batching_comparison(n_reqs: int = 32, n_qubits: int = 2,
                                   depth: int = 2, shots: int = 32,
                                   seed: int = 0,
                                   max_wait_ms: float = 100.0,
                                   trace_sample: float = 0.0,
                                   trace_out: str = None,
                                   service_kwargs: dict = None) -> dict:
    """Warm throughput of ``n_reqs`` service submissions vs the same
    requests dispatched sequentially; returns a JSON-able row.

    ``trace_sample`` > 0 turns on per-request tracing in the measured
    service (the observability-overhead bench varies it); ``trace_out``
    exports the warm round's Chrome-trace JSON (docs/OBSERVABILITY.md);
    ``service_kwargs`` forwards extra :class:`ExecutionService` knobs
    (the integrity-overhead bench varies ``audit_sample`` /
    ``audit_mode`` through it)."""
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    mps = [compile_to_machine(active_reset(qubits) + prog, qchip,
                              n_qubits=n_qubits)
           for prog in rb_ensemble(qubits, depth, n_reqs, seed=seed)]
    C = mps[0].n_cores
    bucket = max(isa.shape_bucket(mp.n_instr) for mp in mps)
    cfg = InterpreterConfig(max_steps=2 * bucket + 64,
                            max_pulses=bucket + 2, max_meas=2,
                            max_resets=2, record_pulses=False)
    rng = np.random.default_rng(11)
    bits = [rng.integers(0, 2, size=(shots, C, 2)).astype(np.int32)
            for _ in mps]

    def run_sequential():
        outs = []
        t0 = time.perf_counter()
        for mp, b in zip(mps, bits):
            # np transfer per call mirrors what the service hands back
            outs.append(jax.tree.map(
                np.asarray, simulate_batch(mp, b, cfg=cfg)))
        return outs, time.perf_counter() - t0

    def run_service(dump_to=None):
        svc = ExecutionService(cfg, max_batch_programs=n_reqs,
                               max_wait_ms=max_wait_ms,
                               max_queue=4 * n_reqs,
                               trace_sample=trace_sample,
                               trace_keep=2 * n_reqs,
                               **(service_kwargs or {}))
        try:
            t0 = time.perf_counter()
            handles = [svc.submit(mp, b) for mp, b in zip(mps, bits)]
            res = [h.result(timeout=600) for h in handles]
            dt = time.perf_counter() - t0
            stats = svc.stats()
            n_events = svc.dump_trace(dump_to) if dump_to else 0
        finally:
            svc.shutdown()
        return res, dt, stats, n_events

    # cold round pays the per-bucket compiles on both sides
    run_sequential()
    run_service()
    # warm round is the measurement
    seq_outs, t_seq = run_sequential()
    traces0 = multi_trace_count()
    svc_res, t_svc, stats, n_events = run_service(dump_to=trace_out)
    warm_retraces = multi_trace_count() - traces0

    mismatch = []
    for i, (a, b) in enumerate(zip(svc_res, seq_outs)):
        for k in b:
            if not np.array_equal(np.asarray(a[k]), np.asarray(b[k])):
                mismatch.append(f'{i}:{k}')
    if mismatch:
        raise AssertionError(
            f'service results diverged from sequential dispatch: '
            f'{mismatch[:8]}')

    return {
        'n_reqs': n_reqs, 'n_qubits': n_qubits, 'depth': depth,
        'shots_per_req': shots, 'bucket_n_instr': bucket,
        'sequential_warm_s': round(t_seq, 4),
        'service_warm_s': round(t_svc, 4),
        'throughput_ratio': round(t_seq / t_svc, 2),
        'dispatches': stats['dispatches'],
        'mean_batch_occupancy': round(stats['coalesce_efficiency'], 2),
        'latency_p50_ms': round(stats['latency_p50_ms'], 3),
        'latency_p99_ms': round(stats['latency_p99_ms'], 3),
        'warm_retraces': warm_retraces,
        'bit_identical': True,
        'trace_sample': trace_sample,
        'trace_events': n_events,
        'audits': stats['integrity']['audits'],
        'audit_mismatches': stats['integrity']['mismatches'],
        'note': 'both sides warm, same generic-engine cfg; ratio is '
                'N per-program dispatches vs coalesced multi-program '
                'dispatch(es); results asserted bit-identical first',
    }


def _workload(n_reqs, n_qubits, depth, shots, seed):
    """(mps, bits, cfg): the RB many-users workload every serve bench
    mode shares — one shape bucket, distinct program contents."""
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    mps = [compile_to_machine(active_reset(qubits) + prog, qchip,
                              n_qubits=n_qubits)
           for prog in rb_ensemble(qubits, depth, n_reqs, seed=seed)]
    bucket = max(isa.shape_bucket(mp.n_instr) for mp in mps)
    cfg = InterpreterConfig(max_steps=2 * bucket + 64,
                            max_pulses=bucket + 2, max_meas=2,
                            max_resets=2, record_pulses=False)
    rng = np.random.default_rng(seed + 11)
    bits = [rng.integers(0, 2, size=(shots, mps[0].n_cores, 2))
            .astype(np.int32) for _ in mps]
    return mps, bits, cfg


def _solo_refs(mps, bits, cfg):
    """Warm per-request references for the bit-identity gate, under
    the same normalized cfg the service will use."""
    ncfg, _ = _normalize_cfg(cfg, isa.shape_bucket(mps[0].n_instr))
    return [jax.tree.map(np.asarray, simulate_batch(mp, b, cfg=ncfg))
            for mp, b in zip(mps, bits)]


def _assert_bit_identical(results, refs, label):
    mismatch = []
    for i, (got, want) in enumerate(zip(results, refs)):
        for k in want:
            if not np.array_equal(np.asarray(got[k]),
                                  np.asarray(want[k])):
                mismatch.append(f'{i}:{k}')
    if mismatch:
        raise AssertionError(
            f'{label}: service results diverged from solo dispatch: '
            f'{mismatch[:8]}')


def _warm_pow2(svc, mp, shots, cfg=None, max_programs=None):
    """AOT-warm every pow2 occupancy of ``mp``'s bucket on every device
    with one ``warmup()`` call.  With ``pad_programs`` (the default)
    live batches only ever dispatch at pow2 occupancies up to the batch
    cap, so a warmed ladder means the timed round is cold-free.  The
    ladder tops out at the cap rounded UP to a pow2 (a 6-deep batch
    pads to 8)."""
    cap = int(max_programs if max_programs is not None
              else svc.max_batch_programs)
    specs, p = [], 1
    while True:
        specs.append(svc.bucket_spec(mp, shots=shots,
                                     n_programs=min(p, cap), cfg=cfg))
        if p >= cap:
            break
        p *= 2
    return svc.warmup(specs)


def multi_device_scaling(dp_list=(1, 2), n_reqs: int = 32,
                         n_qubits: int = 2, depth: int = 2,
                         shots: int = 64, seed: int = 0,
                         max_batch_programs: int = None,
                         max_wait_ms: float = 50.0) -> dict:
    """Pod-scale headline: warm closed-loop shots/s of the SAME
    workload served by 1, 2, ... per-device executors.

    Per dp the service is warmed on every device first (so the timed
    round measures steady-state serving, not compiles), every request's
    result is asserted bit-identical to its solo dispatch BEFORE the
    timed round, and ``stats()`` must show dispatch traffic on every
    device (the bucket is shared, so devices past the home only get
    work via stealing).  ``host_cpu_count`` is recorded because forced
    CPU "devices" share host cores — near-linear scaling needs real
    parallel hardware (TPU chips, or >= dp host cores).
    """
    dp_list = sorted(set(int(d) for d in dp_list))
    if dp_list[0] < 1:
        raise ValueError(f'dp counts must be >= 1; got {dp_list}')
    avail = len(jax.local_devices())
    if dp_list[-1] > avail:
        raise ValueError(
            f'dp={dp_list[-1]} needs that many visible devices; host '
            f'advertises {avail} (off-TPU force them with XLA_FLAGS='
            f'--xla_force_host_platform_device_count={dp_list[-1]})')
    mps, bits, cfg = _workload(n_reqs, n_qubits, depth, shots, seed)
    # enough ripe batches per round that every executor gets work:
    # n_reqs/mb >= 2*dp for the largest dp
    mb = max_batch_programs or max(1, n_reqs // (2 * dp_list[-1]))
    refs = _solo_refs(mps, bits, cfg)
    rows, base_sps = {}, None
    for dp in dp_list:
        svc = ExecutionService(cfg, max_batch_programs=mb,
                               max_wait_ms=max_wait_ms,
                               max_queue=4 * n_reqs, devices=dp)
        try:
            _warm_pow2(svc, mps[0], shots, max_programs=mb)
            # untimed round: residual compiles + the bit-identity gate
            handles = [svc.submit(mp, b) for mp, b in zip(mps, bits)]
            res = [h.result(timeout=600) for h in handles]
            _assert_bit_identical(res, refs, f'dp{dp} pre-timing')
            t0 = time.perf_counter()
            handles = [svc.submit(mp, b) for mp, b in zip(mps, bits)]
            res = [h.result(timeout=600) for h in handles]
            dt = time.perf_counter() - t0
            _assert_bit_identical(res, refs, f'dp{dp} timed')
            stats = svc.stats()
        finally:
            svc.shutdown()
        active = sum(1 for d in stats['devices'] if d['dispatches'] > 0)
        if active < dp:
            raise AssertionError(
                f'dp{dp}: only {active}/{dp} devices saw dispatch '
                f'traffic — routing/stealing failed to spread the load')
        sps = n_reqs * shots / dt
        base_sps = base_sps if base_sps is not None else sps
        rows[f'dp{dp}'] = {
            'warm_s': round(dt, 4),
            'shots_per_sec': round(sps, 1),
            'speedup_vs_dp1': round(sps / base_sps, 2),
            'devices_active': active,
            'steals': stats['steals'],
            'compile_cold': stats['compile']['cold'],
            'compile_warm': stats['compile']['warm'],
            'per_device_dispatches': [d['dispatches']
                                      for d in stats['devices']],
        }
    return {
        'n_reqs': n_reqs, 'n_qubits': n_qubits, 'depth': depth,
        'shots_per_req': shots, 'max_batch_programs': mb,
        'host_cpu_count': os.cpu_count(),
        'bit_identical': True,
        **rows,
        'note': 'warm closed-loop rounds, every device warmed first; '
                'bit-identity vs solo dispatch asserted per request '
                'before timing; shared-core CPU "devices" bound the '
                'speedup by host_cpu_count',
    }


def open_loop_latency(n_reqs: int = 48, rate_hz: float = 40.0,
                      n_qubits: int = 2, depths=(2, 12),
                      shots: int = 16, seed: int = 0, devices=None,
                      max_batch_programs: int = 4,
                      max_wait_ms: float = 5.0, slo: bool = False,
                      warmup_catalog: str = None,
                      trace_sample: float = 0.0,
                      trace_out: str = None) -> dict:
    """Open-loop serving latency: p50/p99 under a seeded Poisson-ish
    mixed-bucket arrival process.

    Closed-loop throughput hides queueing: submitters wait for results,
    so the queue never builds.  Here arrivals follow pre-drawn
    exponential inter-arrival gaps (open loop — a request is submitted
    at its scheduled time no matter how backed up the service is) and
    each request draws one of ``depths``'s shape buckets at random, so
    the coalescer sees the realistic interleaved-tenant mix.  Every
    executable shape is warmed on every device first; the reported
    p50/p99 are the service's own submit-to-done percentiles over
    exactly these requests.  Bit-identity is asserted per request
    before any number is reported.

    ``slo=True`` is the latency-SLO cold-start headline: the SAME
    arrival trace runs twice — first against a cold service with an
    empty ``warmup_catalog`` (the catalog learns each dispatched
    bucket, and every bucket's first timed request eats an XLA
    compile), then against a fresh service that replays the (pow2-
    completed) catalog at startup.  Before the warmed timed round one
    probe request per bucket is asserted bit-identical to the lazily
    compiled solo reference AND asserted to have classified warm; the
    warmed round must then show ``cold_hits == 0`` and a lower p99
    than the unwarmed round — i.e. the catalog provably moved compile
    time out of the serving tail.  ``warmup_catalog`` names the
    catalog file (a temp file when None in slo mode; in normal mode it
    is simply handed to the service for replay + recording).
    """
    rng = np.random.default_rng(seed)
    per_bucket = {d: _workload(max(1, n_reqs // len(depths)), n_qubits,
                               d, shots, seed + 17 * i)
                  for i, d in enumerate(depths)}
    choice = rng.integers(0, len(depths), size=n_reqs)
    gaps = rng.exponential(1.0 / rate_hz, size=n_reqs)
    reqs = []                       # (mp, bits, cfg, depth)
    for i in range(n_reqs):
        d = depths[choice[i]]
        mps, bits, cfg = per_bucket[d]
        j = i % len(mps)
        reqs.append((mps[j], bits[j], cfg, d))
    refs = {d: _solo_refs(*per_bucket[d]) for d in depths}

    def _new_service(catalog=None):
        return ExecutionService(max_batch_programs=max_batch_programs,
                                max_wait_ms=max_wait_ms,
                                max_queue=4 * n_reqs, devices=devices,
                                warmup_catalog=catalog,
                                trace_sample=trace_sample,
                                trace_keep=2 * n_reqs)

    def _await_replay(svc, timeout_s=600.0):
        deadline = time.monotonic() + timeout_s
        while svc.stats()['warmup']['in_progress'] > 0:
            if time.monotonic() > deadline:
                raise AssertionError('catalog replay never finished')
            time.sleep(0.01)

    def _run_arrivals(svc):
        """The timed open-loop round; (results, wall, pre, stats)."""
        pre = svc.stats()
        t0 = time.perf_counter()
        handles = []
        for (mp, bits, cfg, _d), gap in zip(reqs, gaps):
            time.sleep(float(gap))
            handles.append(svc.submit(mp, bits, cfg=cfg))
        results = [h.result(timeout=600) for h in handles]
        wall = time.perf_counter() - t0
        return results, wall, pre, svc.stats()

    def _check_bits(results, label):
        for (mp, bits, cfg, d), got, i in zip(reqs, results,
                                              range(n_reqs)):
            want = refs[d][i % len(refs[d])]
            for k in want:
                if not np.array_equal(np.asarray(got[k]),
                                      np.asarray(want[k])):
                    raise AssertionError(
                        f'{label}: open-loop request {i} (depth {d}) '
                        f'diverged from solo dispatch on {k!r}')

    slo_row = None
    if slo:
        cat_path, tmp_dir = warmup_catalog, None
        if cat_path is None:
            tmp_dir = tempfile.mkdtemp(prefix='dproc-catalog-')
            cat_path = os.path.join(tmp_dir, 'buckets.json')
        try:
            # phase A — cold: the catalog file does not exist yet, so
            # nothing replays; each bucket's first dispatch compiles
            # INSIDE the timed window and the service records every
            # dispatched spec into the catalog.
            svc = _new_service(cat_path)
            try:
                res_a, wall_a, pre_a, st_a = _run_arrivals(svc)
            finally:
                svc.shutdown()
            _check_bits(res_a, 'unwarmed phase')
            cold_unwarmed = (st_a['compile']['cold']
                             - pre_a['compile']['cold'])
            # complete the learned catalog with the full pow2
            # occupancy ladder: phase A's organic batch occupancies
            # depend on arrival timing, and the faster warmed phase
            # can coalesce differently — the ladder covers every shape
            # pad_programs can produce, deterministically.
            cat = BucketCatalog(cat_path)
            for d in depths:
                mps_d, _, cfg_d = per_bucket[d]
                ncfg, _ = _normalize_cfg(
                    cfg_d, isa.shape_bucket(mps_d[0].n_instr))
                tmpl = BucketSpec.from_program(mps_d[0], ncfg)
                p = 1
                while p <= max_batch_programs:
                    cat.record(tmpl.bind(n_programs=p, n_shots=shots))
                    p *= 2
            catalog_specs = len(cat)
            # phase B — warm: a fresh service replays the catalog on
            # its background warmup thread; wait it out, then gate on
            # the probes before timing anything.
            svc = _new_service(cat_path)
            try:
                _await_replay(svc)
                s0 = svc.stats()
                probes = []
                for d in depths:
                    mps_d, bits_d, cfg_d = per_bucket[d]
                    probes.append((d, svc.submit(mps_d[0], bits_d[0],
                                                 cfg=cfg_d)))
                for d, h in probes:
                    got = h.result(timeout=600)
                    want = refs[d][0]
                    for k in want:
                        if not np.array_equal(np.asarray(got[k]),
                                              np.asarray(want[k])):
                            raise AssertionError(
                                f'AOT-warmed probe (depth {d}) '
                                f'diverged from lazily-compiled solo '
                                f'dispatch on {k!r}')
                s1 = svc.stats()
                probe_cold = (s1['compile']['cold']
                              - s0['compile']['cold'])
                if probe_cold:
                    raise AssertionError(
                        f'{probe_cold} probe request(s) classified '
                        f'COLD after catalog replay — AOT warmup '
                        f'missed their shapes')
                results, wall, pre, stats = _run_arrivals(svc)
                if trace_out:
                    svc.dump_trace(trace_out)
            finally:
                svc.shutdown()
            _check_bits(results, 'warmed phase')
        finally:
            if tmp_dir is not None:
                import shutil
                shutil.rmtree(tmp_dir, ignore_errors=True)
        cold_hits = (stats['compile']['cold']
                     - pre['compile']['cold'])
        if cold_hits:
            raise AssertionError(
                f'{cold_hits} cold compile(s) inside the warmed timed '
                f'round — catalog replay did not cover the traffic')
        p99_unwarmed = st_a['latency_p99_ms']
        p99_warmed = stats['latency_p99_ms']
        if cold_unwarmed > 0 and not (p99_warmed < p99_unwarmed):
            raise AssertionError(
                f'warmed p99 {p99_warmed:.3f}ms is not below unwarmed '
                f'p99 {p99_unwarmed:.3f}ms despite {cold_unwarmed} '
                f'cold compile(s) in the unwarmed round')
        slo_row = {
            'catalog_specs': catalog_specs,
            'catalog_path': warmup_catalog,   # None when a temp file
            'unwarmed': {
                'latency_p50_ms': round(st_a['latency_p50_ms'], 3),
                'latency_p99_ms': round(p99_unwarmed, 3),
                'cold_hits': cold_unwarmed,
                'wall_s': round(wall_a, 4),
            },
            'warmed': {
                'latency_p50_ms': round(stats['latency_p50_ms'], 3),
                'latency_p99_ms': round(p99_warmed, 3),
                'cold_hits': cold_hits,
                'wall_s': round(wall, 4),
                'aot_compiled': stats['warmup']['aot_compiled'],
                'replayed': stats['warmup']['replayed'],
            },
            'p99_improvement': (
                round(p99_unwarmed / p99_warmed, 2)
                if p99_warmed > 0 else None),
            'probe_bit_identical': True,
        }
    else:
        svc = _new_service(warmup_catalog)
        try:
            if warmup_catalog:
                _await_replay(svc)
            # warm every pow2 occupancy x bucket x device the open
            # loop can produce (pad_programs keeps live batches on
            # these shapes)
            for d in depths:
                mps, _, cfg = per_bucket[d]
                _warm_pow2(svc, mps[0], shots, cfg=cfg,
                           max_programs=max_batch_programs)
            results, wall, pre, stats = _run_arrivals(svc)
            if trace_out:
                svc.dump_trace(trace_out)
        finally:
            svc.shutdown()
        _check_bits(results, 'open loop')
    occ = stats['batch_occupancy']
    row = {
        'n_reqs': n_reqs, 'offered_rate_hz': rate_hz,
        'achieved_rate_hz': round(n_reqs / wall, 2),
        'depths': list(depths), 'shots_per_req': shots,
        'n_devices': stats['n_devices'],
        'latency_p50_ms': round(stats['latency_p50_ms'], 3),
        'latency_p99_ms': round(stats['latency_p99_ms'], 3),
        'mean_batch_occupancy': round(stats['coalesce_efficiency'], 2),
        'batch_occupancy': {int(k): v for k, v in occ.items()},
        'dispatches': stats['dispatches'],
        'steals': stats['steals'],
        'cold_compiles_timed': (stats['compile']['cold']
                                - pre['compile']['cold']),
        'bit_identical': True,
        'note': 'seeded exponential inter-arrival gaps, mixed shape '
                'buckets, all executable shapes warmed on all devices '
                'first; p50/p99 are service submit-to-done percentiles',
    }
    if slo_row is not None:
        row['slo'] = slo_row
        row['note'] = (
            'slo mode: same seeded arrival trace run cold (catalog '
            'learning, compiles in-window) then warm (catalog replay); '
            'per-bucket probes asserted bit-identical and warm-'
            'classified before timing; headline fields are the warmed '
            'round')
    return row


def availability_under_chaos(n_reqs: int = 80, rate_hz: float = 60.0,
                             n_qubits: int = 2, depth: int = 2,
                             shots: int = 8, seed: int = 0,
                             devices=None,
                             max_batch_programs: int = 4,
                             max_wait_ms: float = 5.0,
                             p_crash: float = 0.08,
                             p_hang: float = 0.02,
                             p_slow: float = 0.10,
                             hang_s: float = 1.0,
                             hang_timeout_s: float = 0.4) -> dict:
    """Availability headline: goodput and p99 latency of an open-loop
    arrival stream while the chaos monkey injects executor crashes,
    hangs and slowdowns under ``_run_batch``.

    The supervision stack (bounded retries, breaker quarantine, hang
    watchdog, canary re-admission) is what keeps goodput near 1.0
    here — with it, an injected fault costs a retry, not a lost or
    hung request.  Every completed request is asserted bit-identical
    to its solo dispatch and every handle must terminate (zero hung)
    BEFORE any number is reported; availability that corrupts bits
    would not be availability."""
    from .chaos import ChaosMonkey, ChaosPlan, soak
    from .supervise import RetryPolicy
    mps, _bits, cfg = _workload(min(n_reqs, 16), n_qubits, depth,
                                shots, seed)
    rng = np.random.default_rng(seed + 23)
    gaps = rng.exponential(1.0 / rate_hz, size=n_reqs)
    svc = ExecutionService(
        cfg, max_batch_programs=max_batch_programs,
        max_wait_ms=max_wait_ms, max_queue=4 * n_reqs,
        devices=devices,
        retry_policy=RetryPolicy(max_attempts=5, backoff_s=0.01),
        hang_timeout_s=hang_timeout_s, breaker_threshold=3,
        breaker_cooldown_ms=100.0, supervise_interval_ms=10.0)
    plan = ChaosPlan(seed=seed, p_crash=p_crash, p_hang=p_hang,
                     p_slow=p_slow, hang_s=hang_s, slow_s=0.01)
    try:
        _warm_pow2(svc, mps[0], shots,
                   max_programs=max_batch_programs)

        def pace(i):
            time.sleep(float(gaps[i]))

        t0 = time.perf_counter()
        with ChaosMonkey(svc, plan) as monkey:
            report = soak(svc, mps, cfg, n_requests=n_reqs,
                          shots=shots, seed=seed,
                          result_timeout_s=600.0, submit_hook=pace)
        wall = time.perf_counter() - t0
        stats = svc.stats()
    finally:
        svc.shutdown()
    if report.hung:
        raise AssertionError(
            f'{report.hung} request(s) never terminated under chaos — '
            f'the supervision layer failed its core guarantee')
    if report.bit_mismatches:
        raise AssertionError(
            f'{report.bit_mismatches} completed request(s) diverged '
            f'from solo dispatch under chaos')
    offered = report.submitted + report.rejected
    return {
        'n_reqs': n_reqs, 'offered_rate_hz': rate_hz,
        'depth': depth, 'shots_per_req': shots,
        'n_devices': stats['n_devices'],
        'injected': dict(sorted(monkey.injected.items())),
        'goodput_fraction': round(
            report.completed / max(offered, 1), 4),
        'completed': report.completed,
        'failed_typed': dict(sorted(report.errors.items())),
        'rejected': report.rejected,
        'hung': report.hung,
        'retries': stats['retries'],
        'retry_exhausted': stats['retry_exhausted'],
        'breaker_trips': stats['breaker_trips'],
        'readmissions': stats['readmissions'],
        'hangs_detected': stats['hangs'],
        'executor_deaths': stats['executor_deaths'],
        # the service's own submit-to-done percentiles (recorded at
        # fulfill time); soak's harvest-order timings would overstate
        'latency_p50_ms': round(stats['latency_p50_ms'], 3),
        'latency_p99_ms': round(stats['latency_p99_ms'], 3),
        'wall_s': round(wall, 4),
        'bit_identical': True,
        'note': 'open-loop seeded arrivals with crash/hang/slowdown '
                'injection under _run_batch; every completion '
                'bit-checked vs solo dispatch and every handle must '
                'terminate before numbers are reported; goodput = '
                'completed / offered',
    }


def tenant_isolation(n_victim: int = 8, greedy_factor: int = 8,
                     n_qubits: int = 2, depth: int = 2,
                     shots: int = 8, seed: int = 0,
                     max_batch_programs: int = 4,
                     max_wait_ms: float = 5.0,
                     victim_weight: float = 8.0,
                     max_p99_ratio: float = 1.5,
                     p99_slack_ms: float = 250.0) -> dict:
    """Tenant isolation headline: what weighted fair queueing buys the
    victim of a greedy neighbor (docs/SERVING.md "Tenants").

    One adversarial arrival shape, two fresh services: a greedy tenant
    dumps its whole backlog (``greedy_factor * n_victim`` requests)
    into the queue, then a victim tenant submits ``n_victim`` requests
    behind it.  Fair-OFF (``tenant_fair=False`` — the pre-tenant
    arrival-order scheduler) makes the victim wait out the entire
    greedy backlog; fair-ON runs deficit round-robin with the victim
    weighted ``victim_weight``x, interleaving it into the very next
    batches.  Both rounds are AOT-warmed first and every victim
    completion is asserted bit-identical to its solo dispatch.  The
    fair-ON round must additionally hold the isolation contract before
    any number is reported: ZERO victim sheds, zero victim quota
    rejections, EXACTLY ``n_victim * shots`` metered victim shots
    (billing ground truth), and a victim p99 within ``max_p99_ratio``
    of the fair-OFF p99 plus ``p99_slack_ms`` (on fast hosts the
    greedy backlog drains quickly and both tails are small — the bound
    guards regression, the reported tails are the evidence).
    """
    n_greedy = greedy_factor * n_victim
    mps, bits, cfg = _workload(n_victim, n_qubits, depth, shots, seed)
    refs = _solo_refs(mps, bits, cfg)
    tenants = {'greedy': {'weight': 1.0},
               'victim': {'weight': float(victim_weight)}}
    rounds = {}
    for label, fair in (('fair_off', False), ('fair_on', True)):
        svc = ExecutionService(
            cfg, max_batch_programs=max_batch_programs,
            max_wait_ms=max_wait_ms,
            max_queue=4 * (n_greedy + n_victim),
            tenants=tenants, tenant_fair=fair)
        try:
            _warm_pow2(svc, mps[0], shots,
                       max_programs=max_batch_programs)
            t0 = time.perf_counter()
            greedy_handles = [
                svc.submit(mps[i % len(mps)], bits[i % len(bits)],
                           tenant='greedy')
                for i in range(n_greedy)]
            victim = []                 # (handle, ref idx, t_submit)
            for i in range(n_victim):
                victim.append((svc.submit(mps[i], bits[i],
                                          tenant='victim'),
                               i, time.perf_counter()))
            lat_ms = []
            for h, i, ts in victim:
                got = h.result(timeout=600)
                lat_ms.append((time.perf_counter() - ts) * 1e3)
                want = refs[i]
                for k in want:
                    if not np.array_equal(np.asarray(got[k]),
                                          np.asarray(want[k])):
                        raise AssertionError(
                            f'{label}: victim request {i} diverged '
                            f'from solo dispatch on {k!r}')
            for h in greedy_handles:
                h.result(timeout=600)
            wall = time.perf_counter() - t0
            ts = svc.stats()['tenants']
        finally:
            svc.shutdown()
        rounds[label] = {
            'victim_p50_ms': round(float(np.percentile(lat_ms, 50)), 3),
            'victim_p99_ms': round(float(np.percentile(lat_ms, 99)), 3),
            'wall_s': round(wall, 4),
            'victim': {k: ts['victim'][k] for k in
                       ('completed', 'shed', 'quota_rejected',
                        'shots')},
            'greedy_completed': ts['greedy']['completed'],
        }
    on, off = rounds['fair_on'], rounds['fair_off']
    v = on['victim']
    if v['shed'] or v['quota_rejected']:
        raise AssertionError(
            f"fair-on round shed {v['shed']} / quota-rejected "
            f"{v['quota_rejected']} victim request(s) — the greedy "
            f'tenant exported its pain')
    if v['shots'] != n_victim * shots:
        raise AssertionError(
            f"victim metered {v['shots']} shots, ground truth is "
            f'{n_victim * shots} — billing is not exactly-once')
    if on['victim_p99_ms'] > (max_p99_ratio * off['victim_p99_ms']
                              + p99_slack_ms):
        raise AssertionError(
            f"fair-on victim p99 {on['victim_p99_ms']}ms exceeds "
            f"{max_p99_ratio}x the fair-off p99 "
            f"{off['victim_p99_ms']}ms (+{p99_slack_ms}ms slack) — "
            f'fair queueing made the victim WORSE')
    return {
        'n_victim': n_victim, 'n_greedy': n_greedy,
        'shots_per_req': shots, 'victim_weight': victim_weight,
        **rounds,
        'victim_p99_ratio_on_vs_off': (
            round(on['victim_p99_ms'] / off['victim_p99_ms'], 3)
            if off['victim_p99_ms'] > 0 else None),
        'bit_identical': True,
        'note': 'greedy backlog submitted first, victim behind it; '
                'fair-off = arrival order, fair-on = DRR with the '
                'victim weighted; victim completions bit-checked vs '
                'solo dispatch; fair-on asserted zero victim sheds, '
                'exact victim billing, bounded p99 before reporting',
    }


def fleet_failover(n_replicas: int = 2, n_reqs: int = 60,
                   rate_hz: float = 30.0, n_qubits: int = 2,
                   depth: int = 2, shots: int = 8, seed: int = 0,
                   kill_at_frac: float = 0.33,
                   kill_window_s: float = 2.0) -> dict:
    """Fleet availability headline: goodput through a timed replica
    SIGKILL (docs/FLEET.md).

    An open-loop stream runs against ``n_replicas`` replica processes
    behind the FleetRouter; a third of the way in, the replica
    carrying the load is SIGKILLed.  The router recovers its in-flight
    requests onto survivors and the fleet monitor respawns the dead
    replica from the shared warm tiers.  The row asserts the contract
    before reporting a single number: zero hung handles, every
    completion bit-identical to solo dispatch, every failure typed,
    and goodput STRICTLY POSITIVE inside ``kill_window_s`` after the
    kill — a fleet that pauses while a replica is down has not
    federated anything."""
    from .chaos import fleet_soak
    from .fleet import Fleet
    mps, bits, cfg = _workload(min(n_reqs, 12), n_qubits, depth,
                               shots, seed)
    kill_i = max(1, int(n_reqs * kill_at_frac))
    t_start = time.perf_counter()
    with Fleet(
            n_replicas,
            service={'max_batch_programs': 4, 'max_wait_ms': 5.0,
                     'max_queue': 4 * n_reqs,
                     'max_est_wait_ms': 5000.0},
            env={'XLA_FLAGS':
                 '--xla_force_host_platform_device_count=1'},
    ) as fleet:
        # warm EVERY replica on the workload bucket directly (bucket
        # affinity would otherwise leave the failover target cold and
        # the kill window would measure its first compile, not the
        # router)
        for rid in fleet.replica_ids():
            fleet.router.call_replica(
                rid, 'submit',
                dict(mp=mps[0], meas_bits=bits[0], cfg=cfg),
                timeout_s=600.0)
        t0 = time.perf_counter()
        report = fleet_soak(
            fleet, mps, cfg, n_requests=n_reqs, shots=shots,
            seed=seed, rate_hz=rate_hz,
            actions=[(kill_i, 'kill', -1)],
            result_timeout_s=600.0)
        wall = time.perf_counter() - t0
        stats = fleet.stats()
    boot_s = t0 - t_start
    if report.hung:
        raise AssertionError(
            f'{report.hung} request(s) never terminated across the '
            f'replica kill — the fleet failed its core guarantee')
    if report.bit_mismatches:
        raise AssertionError(
            f'{report.bit_mismatches} completed request(s) diverged '
            f'from solo dispatch across the replica kill')
    kill_t = next(t for t, m, _ in report.actions if m == 'kill')
    ok_in_kill = report.ok_in_window(kill_t, kill_t + kill_window_s)
    if ok_in_kill == 0:
        raise AssertionError(
            f'goodput hit zero inside the {kill_window_s}s kill '
            f'window — survivors did not absorb the failover')
    offered = report.submitted + report.rejected
    return {
        'n_replicas': n_replicas, 'n_reqs': n_reqs,
        'offered_rate_hz': rate_hz, 'depth': depth,
        'shots_per_req': shots,
        'goodput_fraction': round(
            report.completed / max(offered, 1), 4),
        'completed': report.completed,
        'failed_typed': dict(sorted(report.errors.items())),
        'rejected': report.rejected,
        'hung': report.hung,
        'kill_t_s': round(kill_t, 3),
        'ok_in_kill_window': ok_in_kill,
        'kill_window_goodput_rps': round(
            ok_in_kill / kill_window_s, 2),
        'retries': stats['retries'],
        'retry_exhausted': stats['retry_exhausted'],
        'failovers': stats['failovers'],
        'replica_down': stats['replica_down'],
        'replica_up': stats['replica_up'],
        'respawns': sum(p['respawns']
                        for p in stats['processes'].values()),
        'latency_p50_ms': round(stats['latency_p50_ms'], 3),
        'latency_p99_ms': round(stats['latency_p99_ms'], 3),
        'fleet_boot_s': round(boot_s, 3),
        'wall_s': round(wall, 4),
        'bit_identical': True,
        'note': 'open-loop stream over replica processes; the loaded '
                'replica is SIGKILLed mid-stream and respawned from '
                'the shared warm tiers; every completion bit-checked '
                'vs solo dispatch, every handle must terminate, and '
                'goodput must stay positive through the kill window',
    }


def fleet_observability_overhead(n_replicas: int = 2,
                                 n_reqs: int = 24,
                                 n_qubits: int = 2, depth: int = 2,
                                 shots: int = 8, seed: int = 0,
                                 sampled: float = 0.25) -> dict:
    """What FLEET observability costs: the same closed-loop workload
    through one fleet at trace_sample off / ``sampled`` / full
    (docs/OBSERVABILITY.md "Fleet observability").

    One fleet serves all three rounds (``set_trace_sample`` retunes the
    router's sampler live; the sampling decision rides the wire, so the
    replicas' piggyback cost follows the router's rate with no replica
    restart).  Every replica is warmed on the workload bucket before
    the off round, so round-to-round deltas isolate the tracing tax:
    wire-frame trace ids, replica-side span capture, piggybacked span
    return, and router-side stitching + clock alignment.  The full
    round must actually retain stitched traces — a zero-span "full"
    round would report an overhead it never paid."""
    from .fleet import Fleet
    mps, bits, cfg = _workload(n_reqs, n_qubits, depth, shots, seed)
    refs = _solo_refs(mps, bits, cfg)
    rounds = (('off', 0.0), ('sampled', float(sampled)),
              ('full', 1.0))
    out = {'n_replicas': n_replicas, 'n_reqs': n_reqs,
           'shots_per_req': shots}
    with Fleet(
            n_replicas,
            service={'max_batch_programs': 4, 'max_wait_ms': 5.0,
                     'max_queue': 4 * n_reqs,
                     'max_est_wait_ms': 5000.0},
            env={'XLA_FLAGS':
                 '--xla_force_host_platform_device_count=1'},
    ) as fleet:
        for rid in fleet.replica_ids():
            fleet.router.call_replica(
                rid, 'submit',
                dict(mp=mps[0], meas_bits=bits[0], cfg=cfg),
                timeout_s=600.0)
        # untimed round: residual cold compiles at occupancy > 1 + the
        # bit-identity gate, so the off round is a true warm baseline
        handles = [fleet.submit(mp, b, cfg=cfg)
                   for mp, b in zip(mps, bits)]
        res = [h.result(timeout=600) for h in handles]
        _assert_bit_identical(res, refs, 'fleet-obs pre-timing')
        base_s = None
        for label, sample in rounds:
            fleet.set_trace_sample(sample)
            spans0 = sum(len(c.spans)
                         for c in fleet.router.trace_contexts())
            t0 = time.perf_counter()
            handles = [fleet.submit(mp, b, cfg=cfg)
                       for mp, b in zip(mps, bits)]
            res = [h.result(timeout=600) for h in handles]
            dt = time.perf_counter() - t0
            _assert_bit_identical(res, refs, f'fleet-obs {label}')
            spans = sum(len(c.spans)
                        for c in fleet.router.trace_contexts()) \
                - spans0
            entry = {'trace_sample': sample,
                     'wall_s': round(dt, 4),
                     'reqs_per_sec': round(n_reqs / dt, 2),
                     'stitched_spans': spans}
            if base_s is None:
                base_s = dt
            elif base_s > 0:
                entry['overhead_vs_off'] = round(dt / base_s - 1.0, 4)
            out[label] = entry
        if out['full']['stitched_spans'] <= 0:
            raise AssertionError(
                'full round retained no stitched spans — the fleet '
                'trace path is not actually on, the reported overhead '
                'is fiction')
        if out['off']['stitched_spans'] != 0:
            raise AssertionError(
                f"off round stitched {out['off']['stitched_spans']} "
                f'spans — sampling off must cost (and record) nothing')
    out['bit_identical'] = True
    out['note'] = ('one fleet, three closed-loop rounds with the '
                   'router sampler retuned live; replicas warmed '
                   'before the off round; every completion bit-checked '
                   'vs solo dispatch')
    return out


def compile_front_door(n_tenants: int = 4, n_programs: int = 4,
                       n_qubits: int = 2, depth: int = 4,
                       shots: int = 8, seed: int = 0,
                       stampede_threads: int = 8,
                       max_wait_ms: float = 5.0) -> dict:
    """The multi-tenant compile front door, timed: ``n_tenants`` tenants
    each submit the SAME ``n_programs`` textbook programs (the cloud
    workload: a million users, one RB curriculum).

    Three executions of the N x M duplicate-program traffic: (a)
    uncached compile-per-request — every tenant pays a full
    ``compile_to_machine``; (b) the content-addressed cache, cold — M
    compiles, everything else hits; (c) the cache fully warm.  The row
    asserts the contract before reporting numbers: exactly M cold
    compiles, a 100% warm hit rate, an ``stampede_threads``-way
    concurrent stampede on a fresh program compiling EXACTLY once
    (singleflight), cached programs byte-identical to direct compiles,
    ``submit_source`` results bit-identical to compile+submit, and a
    >= 10x warm speedup.
    """
    import threading
    from ..compilecache import CompileCache, machine_program_bytes
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    sources = [active_reset(qubits) + p
               for p in rb_ensemble(qubits, depth, n_programs,
                                    seed=seed)]
    traffic = sources * n_tenants       # every tenant, every program

    t0 = time.perf_counter()
    direct = {}
    for i, prog in enumerate(traffic):
        mp = compile_to_machine(prog, qchip, n_qubits=n_qubits)
        if i < n_programs:
            direct[i] = mp
    t_uncached = time.perf_counter() - t0

    cache = CompileCache()
    t0 = time.perf_counter()
    for prog in traffic:
        cache.get_or_compile(prog, qchip, n_qubits=n_qubits)
    t_cold = time.perf_counter() - t0
    st = cache.stats()
    cold_compiles, cold_hits = st['misses'], st['hits']
    t0 = time.perf_counter()
    cached = [cache.get_or_compile(prog, qchip, n_qubits=n_qubits)[0]
              for prog in traffic]
    t_warm = time.perf_counter() - t0
    warm_hits = cache.stats()['hits'] - cold_hits

    if cold_compiles != n_programs:
        raise AssertionError(
            f'{cold_compiles} cold compiles for {n_programs} distinct '
            f'programs — content addressing failed to dedup')
    if warm_hits != len(traffic):
        raise AssertionError(
            f'warm pass hit {warm_hits}/{len(traffic)} — cache lost '
            f'entries it should have kept')
    for i in range(n_programs):
        if (machine_program_bytes(cached[i])
                != machine_program_bytes(direct[i])):
            raise AssertionError(
                f'cached program {i} is not byte-identical to its '
                f'direct compile')
    warm_speedup = t_uncached / t_warm
    if warm_speedup < 10.0:
        raise AssertionError(
            f'warm speedup {warm_speedup:.1f}x < 10x — the front door '
            f'is not paying for itself on duplicate traffic')

    # singleflight: a concurrent stampede on a program the cache has
    # never seen must compile exactly once (waiters that arrive after
    # the flight lands count as plain hits — equally deduplicated)
    fresh = active_reset(qubits) + rb_ensemble(
        qubits, depth, 1, seed=seed + 999)[0]
    misses_before = cache.stats()['misses']
    barrier = threading.Barrier(stampede_threads)

    def _stampede():
        barrier.wait()
        cache.get_or_compile(fresh, qchip, n_qubits=n_qubits)

    threads = [threading.Thread(target=_stampede)
               for _ in range(stampede_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stampede_compiles = cache.stats()['misses'] - misses_before
    if stampede_compiles != 1:
        raise AssertionError(
            f'{stampede_threads}-way stampede compiled '
            f'{stampede_compiles} times — singleflight failed')

    # submit_source end-to-end: bit-identical to compile+submit
    svc = ExecutionService(max_wait_ms=max_wait_ms,
                           compile_cache=cache)
    try:
        refs = [svc.submit(direct[i], shots=shots).result(timeout=600)
                for i in range(n_programs)]
        handles = [svc.submit_source(p, qchip, shots=shots,
                                     n_qubits=n_qubits)
                   for p in sources]
        res = [h.result(timeout=600) for h in handles]
        _assert_bit_identical(res, refs, 'submit_source')
        svc_stats = svc.stats()
    finally:
        svc.shutdown()

    cc = svc_stats['compile_cache']
    return {
        'n_tenants': n_tenants, 'n_programs': n_programs,
        'n_qubits': n_qubits, 'depth': depth,
        'traffic_requests': len(traffic),
        'uncached_s': round(t_uncached, 4),
        'cached_cold_s': round(t_cold, 4),
        'cached_warm_s': round(t_warm, 4),
        'cold_compiles': cold_compiles,
        'warm_hit_rate': 1.0,
        'traffic_speedup': round(t_uncached / t_cold, 2),
        'warm_speedup': round(warm_speedup, 1),
        'stampede_threads': stampede_threads,
        'stampede_compiles': stampede_compiles,
        'singleflight_waits': cc['singleflight_waits'],
        'compile_ms_p50': cc['compile_ms_p50'],
        'compile_ms_p99': cc['compile_ms_p99'],
        'bit_identical': True,
        'note': 'N tenants x M duplicate programs; asserted before '
                'reporting: M cold compiles, 100% warm hits, stampede '
                'compiles exactly once, cached bytes == direct bytes, '
                'submit_source bit-identical to compile+submit, '
                'warm speedup >= 10x',
    }


def calibration_loop(knob: str = 'amplitude', n_qubits: int = 2,
                     shots: int = 8, true_x90: float = 0.52,
                     lr: float = None, max_steps: int = None) -> dict:
    """Closed-loop gradient calibration through the serve tier, timed
    (docs/CALIBRATION.md).

    Three runs of one knob's gradient-descent loop (calib/loops.py) on
    a live qchip whose device truth drifted (``true_x90`` vs the
    nominal 0.48 for the amplitude knob):

    1. **writeback run** — the headline: candidates through
       ``submit_source`` under a ``CalibrationSession``, convergence
       ASSERTED before any number reports (tuned value within 5e-3 of
       the truth, stale compile-cache epoch flushed by the
       post-writeback probe, exactly one lineage ``writeback_flush``);
    2. **cold rerun** — the same loop, no writeback, compiling its
       candidate ladder fresh under the post-writeback epoch;
    3. **warm rerun** — identical to (2): every candidate must re-hit
       the compile cache (the warm hit fraction is asserted == 1.0,
       the trajectory asserted identical to the cold rerun's).

    The row reports steps-to-converge, per-run wall time, the warm hit
    fraction and warm speedup, and the service's calibration session
    accounting.
    """
    from ..calib import calibrate
    from ..sim.grad import LossSpec
    spec = (LossSpec(knob='amplitude', x90_amp=true_x90)
            if knob == 'amplitude' else None)
    qchip = make_default_qchip(n_qubits)
    svc = ExecutionService()
    try:
        t0 = time.perf_counter()
        res = calibrate(svc, qchip, knob=knob, qubit='Q0', spec=spec,
                        lr=lr, max_steps=max_steps, shots=shots,
                        n_qubits=n_qubits)
        t_loop = time.perf_counter() - t0
        if not res.converged:
            raise AssertionError(
                f'{knob} loop failed to converge in {res.steps} steps: '
                f'{res.detail.get("reason")}')
        if knob == 'amplitude' and \
                abs(res.params['amp'] - true_x90) > 5e-3:
            raise AssertionError(
                f'converged amp {res.params["amp"]:.5f} not within '
                f'5e-3 of the device truth {true_x90}')
        if res.fp_before == res.fp_after:
            raise AssertionError('writeback did not move the '
                                 'calibration epoch')
        if not 1 <= res.flushed <= res.steps:
            raise AssertionError(
                f'post-writeback probe flushed {res.flushed} entries '
                f'for a {res.steps}-step loop')
        cache = svc.compile_cache
        if cache.stats()['writeback_flushes'] != 1:
            raise AssertionError(
                f'{cache.stats()["writeback_flushes"]} lineage '
                f'writeback flushes for one writeback')

        # cold/warm rerun pair under the post-writeback epoch: the
        # trajectory depends only on (start, lr, spec), so the reruns
        # retrace the same candidate ladder — first compiles it,
        # second must re-hit every rung
        t0 = time.perf_counter()
        cold = calibrate(svc, qchip, knob=knob, qubit='Q0', spec=spec,
                         lr=lr, max_steps=max_steps, shots=shots,
                         n_qubits=n_qubits, write_back=False)
        t_cold = time.perf_counter() - t0
        hits0 = cache.stats()['hits']
        t0 = time.perf_counter()
        warm = calibrate(svc, qchip, knob=knob, qubit='Q0', spec=spec,
                         lr=lr, max_steps=max_steps, shots=shots,
                         n_qubits=n_qubits, write_back=False)
        t_warm = time.perf_counter() - t0
        warm_hit_fraction = \
            (cache.stats()['hits'] - hits0) / max(warm.steps, 1)
        if warm.losses != cold.losses:
            raise AssertionError('warm rerun trajectory diverged from '
                                 'the cold rerun')
        if warm_hit_fraction < 1.0:
            raise AssertionError(
                f'warm rerun hit only {warm_hit_fraction:.2f} of its '
                f'candidate compiles — the calibration ladder fell '
                f'out of the cache')
        calib_stats = svc.stats()['calibration']
    finally:
        svc.shutdown()
    return {
        'knob': knob, 'n_qubits': n_qubits, 'shots': shots,
        'steps_to_converge': res.steps,
        'converged_params': {k: round(v, 6)
                             for k, v in res.params.items()},
        'loss_first': res.losses[0], 'loss_final': res.losses[-1],
        'epoch_entries_flushed': res.flushed,
        'writeback_flushes': 1,
        'loop_s': round(t_loop, 4),
        'cold_rerun_s': round(t_cold, 4),
        'warm_rerun_s': round(t_warm, 4),
        'warm_hit_fraction': warm_hit_fraction,
        'warm_speedup': round(t_cold / t_warm, 2) if t_warm else None,
        'sessions': calib_stats,
        'note': 'asserted before reporting: convergence to the drifted '
                'device truth, epoch moved by writeback, exactly the '
                'stale epoch flushed (one lineage flush), warm rerun '
                '100% cache hits with an identical trajectory',
    }


def _main(argv=None):
    """Standalone entry: ``python -m distributed_processor_tpu.serve.
    benchmark scaling|openloop ...`` prints one JSON row — bench.py
    shells out here with ``--xla_force_host_platform_device_count`` to
    get a multi-device pool on hosts whose parent process sees fewer
    devices than the requested dp."""
    import argparse
    import json
    ap = argparse.ArgumentParser(
        prog='python -m distributed_processor_tpu.serve.benchmark')
    sub = ap.add_subparsers(dest='mode', required=True)
    s = sub.add_parser('scaling', help='closed-loop dp scaling row')
    s.add_argument('--dp', default='1,2')
    s.add_argument('--reqs', type=int, default=32)
    s.add_argument('--shots', type=int, default=64)
    s.add_argument('--depth', type=int, default=2)
    s.add_argument('--qubits', type=int, default=2)
    s.add_argument('--seed', type=int, default=0)
    o = sub.add_parser('openloop', help='open-loop latency row')
    o.add_argument('--reqs', type=int, default=48)
    o.add_argument('--rate', type=float, default=40.0)
    o.add_argument('--shots', type=int, default=16)
    o.add_argument('--depths', default='2,12')
    o.add_argument('--devices', type=int, default=None)
    o.add_argument('--qubits', type=int, default=2)
    o.add_argument('--seed', type=int, default=0)
    o.add_argument('--slo', action='store_true',
                   help='cold-vs-warm catalog SLO comparison')
    o.add_argument('--warmup-catalog', default=None,
                   help='bucket catalog path to replay/record')
    f = sub.add_parser('frontdoor', help='compile front-door row')
    f.add_argument('--tenants', type=int, default=4)
    f.add_argument('--programs', type=int, default=4)
    f.add_argument('--depth', type=int, default=4)
    f.add_argument('--shots', type=int, default=8)
    f.add_argument('--qubits', type=int, default=2)
    f.add_argument('--seed', type=int, default=0)
    f.add_argument('--stampede', type=int, default=8)
    t = sub.add_parser('tenants', help='tenant-isolation row')
    t.add_argument('--victims', type=int, default=8)
    t.add_argument('--greedy-factor', type=int, default=8)
    t.add_argument('--shots', type=int, default=8)
    t.add_argument('--depth', type=int, default=2)
    t.add_argument('--qubits', type=int, default=2)
    t.add_argument('--seed', type=int, default=0)
    t.add_argument('--victim-weight', type=float, default=8.0)
    c = sub.add_parser('chaos', help='availability-under-chaos row')
    c.add_argument('--reqs', type=int, default=80)
    c.add_argument('--rate', type=float, default=60.0)
    c.add_argument('--shots', type=int, default=8)
    c.add_argument('--depth', type=int, default=2)
    c.add_argument('--devices', type=int, default=None)
    c.add_argument('--qubits', type=int, default=2)
    c.add_argument('--seed', type=int, default=0)
    c.add_argument('--p-crash', type=float, default=0.08)
    c.add_argument('--p-hang', type=float, default=0.02)
    c.add_argument('--p-slow', type=float, default=0.10)
    args = ap.parse_args(argv)
    if args.mode == 'scaling':
        row = multi_device_scaling(
            dp_list=[int(x) for x in args.dp.split(',') if x],
            n_reqs=args.reqs, n_qubits=args.qubits, depth=args.depth,
            shots=args.shots, seed=args.seed)
    elif args.mode == 'openloop':
        row = open_loop_latency(
            n_reqs=args.reqs, rate_hz=args.rate, n_qubits=args.qubits,
            depths=[int(x) for x in args.depths.split(',') if x],
            shots=args.shots, seed=args.seed, devices=args.devices,
            slo=args.slo, warmup_catalog=args.warmup_catalog)
    elif args.mode == 'tenants':
        row = tenant_isolation(
            n_victim=args.victims, greedy_factor=args.greedy_factor,
            n_qubits=args.qubits, depth=args.depth, shots=args.shots,
            seed=args.seed, victim_weight=args.victim_weight)
    elif args.mode == 'frontdoor':
        row = compile_front_door(
            n_tenants=args.tenants, n_programs=args.programs,
            n_qubits=args.qubits, depth=args.depth, shots=args.shots,
            seed=args.seed, stampede_threads=args.stampede)
    else:
        row = availability_under_chaos(
            n_reqs=args.reqs, rate_hz=args.rate, n_qubits=args.qubits,
            depth=args.depth, shots=args.shots, seed=args.seed,
            devices=args.devices, p_crash=args.p_crash,
            p_hang=args.p_hang, p_slow=args.p_slow)
    print(json.dumps(row))


if __name__ == '__main__':
    _main()
