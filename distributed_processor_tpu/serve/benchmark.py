"""Continuous-batching benchmark: the service's reason to exist, timed.

One workload, two executions: ``n_reqs`` independent single-program
requests (distinct random RB sequences — the realistic many-users
shape) run (a) sequentially, one ``simulate_batch`` dispatch per
program, and (b) through :class:`~.service.ExecutionService`, which
coalesces them into shape-bucketed multi-program dispatches.  Both
sides use the same normalized generic-engine cfg and both rounds are
timed WARM (a cold round runs first to pay the one-per-bucket
compile), so the ratio isolates the dispatch economics: N host
round-trips vs ~1.  Results are asserted bit-identical before any
number is reported.

Shared by the ``continuous_batching`` row in bench.py and the
``serve-bench`` CLI subcommand.
"""

from __future__ import annotations

import time

import numpy as np

import jax

from .. import isa
from ..models import active_reset, make_default_qchip, rb_ensemble
from ..pipeline import compile_to_machine
from ..sim.interpreter import (InterpreterConfig, multi_trace_count,
                               simulate_batch)
from .service import ExecutionService


def continuous_batching_comparison(n_reqs: int = 32, n_qubits: int = 2,
                                   depth: int = 2, shots: int = 32,
                                   seed: int = 0,
                                   max_wait_ms: float = 100.0) -> dict:
    """Warm throughput of ``n_reqs`` service submissions vs the same
    requests dispatched sequentially; returns a JSON-able row."""
    qubits = [f'Q{i}' for i in range(n_qubits)]
    qchip = make_default_qchip(n_qubits)
    mps = [compile_to_machine(active_reset(qubits) + prog, qchip,
                              n_qubits=n_qubits)
           for prog in rb_ensemble(qubits, depth, n_reqs, seed=seed)]
    C = mps[0].n_cores
    bucket = max(isa.shape_bucket(mp.n_instr) for mp in mps)
    cfg = InterpreterConfig(max_steps=2 * bucket + 64,
                            max_pulses=bucket + 2, max_meas=2,
                            max_resets=2, record_pulses=False)
    rng = np.random.default_rng(11)
    bits = [rng.integers(0, 2, size=(shots, C, 2)).astype(np.int32)
            for _ in mps]

    def run_sequential():
        outs = []
        t0 = time.perf_counter()
        for mp, b in zip(mps, bits):
            # np transfer per call mirrors what the service hands back
            outs.append(jax.tree.map(
                np.asarray, simulate_batch(mp, b, cfg=cfg)))
        return outs, time.perf_counter() - t0

    def run_service():
        svc = ExecutionService(cfg, max_batch_programs=n_reqs,
                               max_wait_ms=max_wait_ms,
                               max_queue=4 * n_reqs)
        try:
            t0 = time.perf_counter()
            handles = [svc.submit(mp, b) for mp, b in zip(mps, bits)]
            res = [h.result(timeout=600) for h in handles]
            dt = time.perf_counter() - t0
            stats = svc.stats()
        finally:
            svc.shutdown()
        return res, dt, stats

    # cold round pays the per-bucket compiles on both sides
    run_sequential()
    run_service()
    # warm round is the measurement
    seq_outs, t_seq = run_sequential()
    traces0 = multi_trace_count()
    svc_res, t_svc, stats = run_service()
    warm_retraces = multi_trace_count() - traces0

    mismatch = []
    for i, (a, b) in enumerate(zip(svc_res, seq_outs)):
        for k in b:
            if not np.array_equal(np.asarray(a[k]), np.asarray(b[k])):
                mismatch.append(f'{i}:{k}')
    if mismatch:
        raise AssertionError(
            f'service results diverged from sequential dispatch: '
            f'{mismatch[:8]}')

    return {
        'n_reqs': n_reqs, 'n_qubits': n_qubits, 'depth': depth,
        'shots_per_req': shots, 'bucket_n_instr': bucket,
        'sequential_warm_s': round(t_seq, 4),
        'service_warm_s': round(t_svc, 4),
        'throughput_ratio': round(t_seq / t_svc, 2),
        'dispatches': stats['dispatches'],
        'mean_batch_occupancy': round(stats['coalesce_efficiency'], 2),
        'latency_p50_ms': round(stats['latency_p50_ms'], 3),
        'latency_p99_ms': round(stats['latency_p99_ms'], 3),
        'warm_retraces': warm_retraces,
        'bit_identical': True,
        'note': 'both sides warm, same generic-engine cfg; ratio is '
                'N per-program dispatches vs coalesced multi-program '
                'dispatch(es); results asserted bit-identical first',
    }
