"""Replica entry point: one ExecutionService process on the fleet wire.

``python -m distributed_processor_tpu.serve.replica_main '<json>'``
boots one replica of the fleet (docs/FLEET.md): it applies the
environment knobs from the config BEFORE anything imports jax (device
count and platform are import-time decisions), builds an
:class:`~.service.ExecutionService` from the ``service`` kwargs, wraps
it in a :class:`~.transport.ReplicaServer`, and prints one JSON ready
line (``{"ready": true, "port": ..., "pid": ...}``) on stdout so the
spawning :class:`~.fleet.Fleet` learns the bound port without a port
race.  It then blocks until a ``shutdown`` wire op or SIGTERM arrives.

Config schema (all keys optional)::

    {
      "env":          {"JAX_PLATFORMS": "cpu", "XLA_FLAGS": "..."},
      "jax_cache_dir": "<shared persistent XLA compile cache>",
      "interp_cfg":   {"max_steps": 192, ...},   # InterpreterConfig
      "service":      {"devices": "all", "compile_cache_dir": ...,
                       "warmup_catalog": ..., ...},
      "host": "127.0.0.1", "port": 0, "rid": "r0"
    }

``jax_cache_dir`` / ``compile_cache_dir`` / ``warmup_catalog`` are the
three shared warm tiers: pointing every replica of a fleet at the same
directories means a freshly respawned replica replays its warmup from
what its PEERS compiled and persisted — the zero-cold-restart property
the fleet tests assert.

Observability env knobs (config keys win when both are set):
``DPROC_TRACE_SAMPLE`` sets the service's local ``trace_sample`` and
``DPROC_FLIGHT_DIR`` its ``flight_dump_dir`` — note the fleet router's
sampling decision arrives ON THE WIRE per request regardless of the
local rate (docs/OBSERVABILITY.md "Fleet observability").
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading


def main(argv=None) -> int:
    argv = sys.argv if argv is None else argv
    cfg = json.loads(argv[1]) if len(argv) > 1 and argv[1] else {}

    # environment first: device count / platform are read at jax import
    for k, v in (cfg.get('env') or {}).items():
        os.environ[k] = str(v)

    import jax
    if cfg.get('jax_cache_dir'):
        jax.config.update('jax_compilation_cache_dir',
                          cfg['jax_cache_dir'])
        jax.config.update('jax_persistent_cache_min_compile_time_secs',
                          0.0)

    from ..sim.interpreter import InterpreterConfig
    from .service import ExecutionService
    from .transport import ReplicaServer

    icfg = None
    if cfg.get('interp_cfg'):
        icfg = InterpreterConfig(**cfg['interp_cfg'])
    skw = dict(cfg.get('service') or {})
    # observability env knobs (config wins; env covers replicas booted
    # outside Fleet, e.g. by hand against a remote router)
    if os.environ.get('DPROC_TRACE_SAMPLE'):
        skw.setdefault('trace_sample',
                       float(os.environ['DPROC_TRACE_SAMPLE']))
    if os.environ.get('DPROC_FLIGHT_DIR'):
        skw.setdefault('flight_dump_dir',
                       os.environ['DPROC_FLIGHT_DIR'])
    svc = ExecutionService(icfg, name=cfg.get('rid'), **skw)

    stop = threading.Event()
    server = ReplicaServer(svc, host=cfg.get('host', '127.0.0.1'),
                           port=int(cfg.get('port', 0)),
                           on_shutdown=stop.set)
    signal.signal(signal.SIGTERM, lambda *_a: stop.set())

    print(json.dumps({'ready': True, 'rid': cfg.get('rid'),
                      'host': server.address[0],
                      'port': server.address[1],
                      'pid': os.getpid()}), flush=True)
    stop.wait()
    server.close()
    svc.shutdown(drain=False)
    return 0


if __name__ == '__main__':
    sys.exit(main())
