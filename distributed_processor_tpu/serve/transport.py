"""Fleet wire protocol: framed-pickle RPC between router and replicas.

The fleet tier (docs/FLEET.md) runs N :class:`~.service.ExecutionService`
replicas as separate OS processes; this module is the only thing that
crosses the process boundary.  The protocol is deliberately minimal —
length-prefixed pickle frames over a localhost TCP socket (the same
wire works across hosts), request-id multiplexed so ONE connection
carries many in-flight submissions:

    client -> server   (req_id, op, payload)
    server -> client   (req_id, ok: bool, payload)

``op`` is one of ``submit`` / ``submit_source`` / ``stats`` / ``ping``
/ ``gossip`` / ``fleet-metrics`` / ``flight`` / ``shutdown``.  A
``submit`` gets exactly one response — sent when the request RESOLVES,
so admission errors (``QueueFullError``, ``OverloadError``), typed
program failures (``FaultError``, validation) and results all ride the
same frame, preserving the
:func:`~..sim.interpreter.is_infrastructure_error` taxonomy across the
wire: both sides share this codebase, so exceptions pickle as their
real types and the router can re-apply the retry rules the in-process
supervision layer uses.

Fleet observability rides the same frames (docs/OBSERVABILITY.md
"Fleet observability"): a submit payload may carry ``_trace``, the
router's trace id for a SAMPLED request — the server opens a forced
replica-side :class:`TraceContext` for it and piggybacks the recorded
spans back on the resolve reply as ``{'__trace__': {'spans': [...],
'mono_recv': ..., 'mono_send': ...}, 'result': <stats>}`` (the two
``mono`` stamps are replica-clock bounds of the server-side window, so
the router can split wire time from replica time).  ``gossip`` returns
the stats digest plus the replica's monotonic clock (the router's
clock-offset probe) and a flight-ring digest; ``fleet-metrics``
returns the replica's whole metrics-registry snapshot for labeled
re-exposition; ``flight`` returns the full flight ring for the
federated post-mortem pull.

Every frame carries a CRC32 content checksum in its header
(docs/ROBUSTNESS.md "Integrity"): the sender digests the pickle bytes,
the receiver verifies before unpickling, and a mismatch — or a
declared length past the wire bound, or a payload that truncates
mid-read — raises :class:`WireCorruptionError` and tears the
connection down.  A garbled frame therefore becomes a typed,
connection-scoped event the fleet retry machinery recovers from
(:class:`ReplicaLostError` -> re-dispatch; the router re-dials torn
connections on the gossip cadence), never a hang and never a
silently-wrong unpickle.  The check is a single C-speed pass over
bytes already in hand — negligible next to the pickle itself — so it
is always on.

Server side, submissions are enqueued into the service from the
connection's reader thread (``ExecutionService.submit`` never blocks on
execution) and a small waiter pool sends each response when its handle
resolves — a slow batch never stalls the connection.  Client side, a
reader thread demultiplexes responses to per-request callbacks; a dead
connection fails every pending callback with :class:`ReplicaLostError`
(a plain RuntimeError: infrastructure-class, hence retryable at the
fleet level) and fires ``on_lost`` exactly once.

All threads carry the ``dproc-serve`` name prefix, so the conftest
thread-leak probe holds this tier to the same no-leak contract as the
service's dispatchers.
"""

from __future__ import annotations

import itertools
import pickle
import socket
import struct
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor

from ..utils import profiling

WIRE_THREAD_PREFIX = 'dproc-serve-wire'

_HDR = struct.Struct('>II')   # (payload length, payload CRC32)
_MAX_FRAME = 1 << 29          # 512 MiB: desync/corruption guard

OPS = ('submit', 'submit_source', 'submit_rounds', 'close_stream',
       'stats', 'ping', 'gossip', 'fleet-metrics', 'flight',
       'shutdown')


class ReplicaLostError(RuntimeError):
    """The connection to a replica died (process SIGKILLed, socket
    closed, unreadable frame) with requests still in flight.
    Deliberately a plain RuntimeError so
    :func:`~..sim.interpreter.is_infrastructure_error` classifies it
    retryable — replica loss is the fleet-level analog of an executor
    crash."""


class WireCorruptionError(ConnectionError):
    """A frame failed its integrity checks: header CRC32 mismatch or a
    declared length past the wire bound.  A ConnectionError subclass
    on purpose — every existing teardown path (server per-connection
    loop, client reader loop) already treats ConnectionError as
    "this connection is no longer trustworthy", which is exactly the
    right response to corruption: reset, re-dial, retry; NEVER unpickle
    the garbled bytes."""


# test/chaos hook (docs/ROBUSTNESS.md "Integrity"): a callable
# ``bytes -> bytes`` applied to every received payload BEFORE the CRC
# check, simulating corruption on the wire so detection — not
# injection — is what gets exercised.  Process-global by design: the
# chaos driver corrupts every connection the process reads.
_wire_corruptor = None


def install_wire_corruptor(fn):
    """Install (or with None, remove) the receive-path corruptor;
    returns the previous hook so tests can restore it."""
    global _wire_corruptor
    prev = _wire_corruptor
    _wire_corruptor = fn
    return prev


def send_frame(sock: socket.socket, obj, lock: threading.Lock) -> int:
    """Pickle ``obj`` and write one CRC-stamped length-prefixed frame.
    ``lock`` serializes concurrent writers (responses from the waiter
    pool interleave with reader-thread error replies).  Returns the
    total bytes written (header + payload) so callers can meter
    bytes-on-wire per tenant (docs/SERVING.md "Tenants")."""
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    with lock:
        sock.sendall(_HDR.pack(len(data), zlib.crc32(data)) + data)
    return _HDR.size + len(data)


def recv_frame(sock: socket.socket):
    """Read and verify one frame; raises :class:`WireCorruptionError`
    on an oversized declared length or a CRC mismatch, plain
    ConnectionError on EOF / mid-frame truncation.  The payload is
    only unpickled after its checksum passes."""
    obj, _n = recv_frame_sized(sock)
    return obj


def recv_frame_sized(sock: socket.socket):
    """Like :func:`recv_frame` but returns ``(obj, nbytes)`` where
    ``nbytes`` counts header + payload as received — the server side
    uses it to attribute request bytes to the submitting tenant."""
    head = _recv_exact(sock, _HDR.size)
    n, crc = _HDR.unpack(head)
    if n > _MAX_FRAME:
        profiling.counter_inc('integrity.wire_checksum_fail')
        raise WireCorruptionError(
            f'frame of {n} bytes exceeds wire bound '
            f'({_MAX_FRAME}): header corrupt or stream desynced')
    data = _recv_exact(sock, n)
    if _wire_corruptor is not None:
        data = _wire_corruptor(data)
    if zlib.crc32(data) != crc:
        profiling.counter_inc('integrity.wire_checksum_fail')
        raise WireCorruptionError(
            f'frame CRC mismatch ({n} bytes): payload corrupted on '
            f'the wire')
    return pickle.loads(data), _HDR.size + n


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b''
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError('connection closed mid-frame')
        buf += chunk
    return buf


def _picklable_error(exc: BaseException) -> BaseException:
    """The error as it will cross the wire: the exception itself when
    it pickle-round-trips, else a RuntimeError carrying its type name
    (still infrastructure-class — an unpicklable error is by
    construction not one of the typed program-class failures, which
    all round-trip; tests pin FaultError/ProgramValidationError)."""
    try:
        pickle.loads(pickle.dumps(exc))
        return exc
    except Exception:
        return RuntimeError(f'{type(exc).__name__}: {exc}')


class ReplicaServer:
    """Serves one :class:`ExecutionService` over the fleet wire.

    ``on_shutdown`` (optional) runs when a ``shutdown`` op arrives —
    the replica main loop uses it to exit.  ``close()`` stops
    accepting, closes every connection and joins every wire thread; it
    does NOT shut the service down (the owner does).
    """

    def __init__(self, svc, host: str = '127.0.0.1', port: int = 0,
                 max_waiters: int = 32, on_shutdown=None,
                 flight_tail: int = 32):
        self._svc = svc
        self._on_shutdown = on_shutdown
        self._flight_tail = int(flight_tail)
        self._closing = False
        self._conns = set()
        self._conns_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max_waiters,
            thread_name_prefix=f'{WIRE_THREAD_PREFIX}-wait')
        self._sock = socket.create_server((host, port))
        self.address = self._sock.getsockname()
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f'{WIRE_THREAD_PREFIX}-accept', daemon=True)
        self._accept_thread.start()

    # -- accept / per-connection ----------------------------------------

    def _accept_loop(self) -> None:
        while not self._closing:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return                     # closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                if self._closing:
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,),
                name=f'{WIRE_THREAD_PREFIX}-conn', daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        wlock = threading.Lock()
        try:
            while True:
                (req_id, op, payload), nbytes = recv_frame_sized(conn)
                self._dispatch(conn, wlock, req_id, op, payload,
                               nbytes)
        except (ConnectionError, OSError, EOFError,
                pickle.UnpicklingError):
            pass                           # router went away
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, conn, wlock, req_id, op, payload,
                  nbytes: int = 0) -> None:
        try:
            if op in ('submit', 'submit_source', 'submit_rounds'):
                t_recv = time.monotonic()
                # request-frame bytes bill to the submitting tenant
                # (docs/SERVING.md "Tenants"); response bytes are
                # metered when the resolve reply is sent
                tenant = payload.get('tenant')
                self._svc.meter_wire(tenant, nbytes)
                # `_trace` = the router's sampling decision for this
                # request: open a forced replica-side context so the
                # spans recorded here ship back on the resolve reply
                trace_id = payload.pop('_trace', None)
                # `_crc` = the router's submit-time program digest
                # (docs/ROBUSTNESS.md "Integrity"): verify the decoded
                # program content-matches what the caller submitted —
                # a frame CRC covers the wire, this covers the
                # pickle/unpickle round trip and anything between
                # digest and send.  Its presence also asks for a
                # result-stat digest on the resolve reply.
                want_crc = payload.pop('_crc', None)
                if want_crc is not None \
                        and payload.get('mp') is not None:
                    from ..integrity import (IntegrityError,
                                             program_digest)
                    got_crc = program_digest(payload['mp'])
                    if got_crc != want_crc:
                        profiling.counter_inc(
                            'integrity.wire_checksum_fail')
                        raise IntegrityError(
                            f'submitted program digest mismatch '
                            f'(want {want_crc:#010x}, decoded '
                            f'{got_crc:#010x}): corrupted in transit')
                kw = dict(payload)
                if trace_id is not None:
                    kw['_handle'] = self._svc.traced_handle(
                        int(trace_id))
                if op == 'submit':
                    handle = self._svc.submit(**kw)
                elif op == 'submit_rounds':
                    # stream chunk: same resolve-time reply path, so
                    # every chunk's result ships as one incremental
                    # frame (docs/SERVING.md "Streaming sessions")
                    handle = self._svc.submit_rounds(**kw)
                else:
                    handle = self._svc.submit_source(**kw)
                self._pool.submit(self._send_on_resolve, conn, wlock,
                                  req_id, handle, t_recv,
                                  want_crc is not None, tenant)
                return
            if op == 'close_stream':
                self._reply(conn, wlock, req_id, True, {
                    'closed': self._svc.close_stream(
                        int(payload['sid']))})
                return
            if op == 'stats':
                self._reply(conn, wlock, req_id, True,
                            self._svc.stats())
                return
            if op == 'ping':
                self._reply(conn, wlock, req_id, True,
                            {'pong': True, 'mono': time.monotonic()})
                return
            if op == 'gossip':
                # one frame = heartbeat + clock probe + flight digest:
                # the router re-arms liveness, feeds its offset
                # estimator, and caches the event tail for the
                # federated post-mortem (docs/OBSERVABILITY.md)
                fl = self._svc.flight_recorder
                self._reply(conn, wlock, req_id, True, {
                    'stats': self._svc.stats(),
                    'mono': time.monotonic(),
                    'flight': {'recorded': fl.recorded,
                               'dropped': fl.dropped,
                               'counts': fl.counts(),
                               'tail': fl.events()[-self._flight_tail:]},
                })
                return
            if op == 'fleet-metrics':
                self._reply(conn, wlock, req_id, True, {
                    'mono': time.monotonic(),
                    'metrics': profiling.registry().snapshot()})
                return
            if op == 'flight':
                doc = self._svc.flight_recorder.to_json()
                doc['mono'] = time.monotonic()
                self._reply(conn, wlock, req_id, True, doc)
                return
            if op == 'shutdown':
                self._reply(conn, wlock, req_id, True, {'bye': True})
                if self._on_shutdown is not None:
                    self._on_shutdown()
                return
            raise ValueError(f'unknown wire op {op!r}')
        except BaseException as exc:       # noqa: BLE001 - typed reply
            self._reply(conn, wlock, req_id, False,
                        _picklable_error(exc))

    def _send_on_resolve(self, conn, wlock, req_id, handle,
                         t_recv: float = None,
                         want_digest: bool = False,
                         tenant: str = None) -> None:
        # blocks until the service resolves the handle: shutdown
        # force-fails every unresolved handle, so this always returns
        try:
            exc = handle.exception(timeout=None)
        except BaseException as exc2:      # noqa: BLE001
            exc = exc2
        try:
            if exc is None:
                result = handle.result()
                if want_digest:
                    # stamp the result-stat digest before any other
                    # wrapping (innermost: the router unwraps the
                    # trace envelope first, then verifies this) so the
                    # digest covers exactly the stat block the tenant
                    # would receive
                    from ..integrity import stats_digest
                    result = {'__icrc__': stats_digest(result),
                              'result': result}
                if handle._trace is not None:
                    # piggyback the replica-side spans (replica-clock
                    # times; the two mono stamps bound the server-side
                    # window so the router can price the wire hop)
                    result = {'__trace__': {
                        'spans': handle.trace(),
                        'mono_recv': t_recv,
                        'mono_send': time.monotonic()},
                        'result': result}
                n = self._reply(conn, wlock, req_id, True, result)
            else:
                n = self._reply(conn, wlock, req_id, False,
                                _picklable_error(exc))
            # response bytes bill to the same tenant as the request
            self._svc.meter_wire(tenant, n)
        except (ConnectionError, OSError):
            pass                           # router gone: drop response

    @staticmethod
    def _reply(conn, wlock, req_id, ok, payload) -> int:
        return send_frame(conn, (req_id, ok, payload), wlock)

    def close(self) -> None:
        self._closing = True
        try:
            # shutdown() wakes a concurrently-blocked accept() (close()
            # alone does not on Linux), so the accept thread always
            # joins instead of outliving the server
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._pool.shutdown(wait=True)
        self._accept_thread.join(timeout=5.0)


class ReplicaClient:
    """Router-side end of one replica connection.

    ``call_async(op, payload, on_done)`` sends a frame and returns its
    request id; ``on_done(ok, payload)`` fires from the reader thread
    when the response lands.  ``forget(req_id)`` drops a pending
    callback — the router's failover path uses it so a straggler
    response from a replica that was declared dead (and whose request
    was retried elsewhere) is discarded, the wire-level mirror of the
    handle's stale-attempt-token rule.  When the connection dies, every
    pending callback fails with :class:`ReplicaLostError` and
    ``on_lost(exc)`` fires exactly once.
    """

    def __init__(self, address, *, connect_timeout_s: float = 10.0,
                 on_lost=None):
        self.address = tuple(address)
        self._on_lost = on_lost
        self._sock = socket.create_connection(
            self.address, timeout=connect_timeout_s)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._wlock = threading.Lock()
        self._plock = threading.Lock()
        self._pending: dict = {}           # req_id -> on_done
        self._ids = itertools.count(1)
        self._lost = None                  # the ReplicaLostError, once
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f'{WIRE_THREAD_PREFIX}-client', daemon=True)
        self._reader.start()

    @property
    def alive(self) -> bool:
        return self._lost is None

    def call_async(self, op: str, payload, on_done) -> int:
        with self._plock:
            if self._lost is not None:
                raise ReplicaLostError(
                    f'replica {self.address} lost: {self._lost}')
            req_id = next(self._ids)
            self._pending[req_id] = on_done
        try:
            send_frame(self._sock, (req_id, op, payload), self._wlock)
        except (OSError, ConnectionError) as exc:
            self._fail_all(exc)
            raise ReplicaLostError(
                f'replica {self.address} lost: {exc}') from exc
        return req_id

    def call(self, op: str, payload=None, timeout_s: float = 30.0):
        """Synchronous round trip; raises the remote error, or
        :class:`ReplicaLostError`/:class:`TimeoutError`."""
        ev = threading.Event()
        box = {}

        def done(ok, resp):
            box['ok'], box['resp'] = ok, resp
            ev.set()

        req_id = self.call_async(op, payload or {}, done)
        if not ev.wait(timeout_s):
            self.forget(req_id)
            raise TimeoutError(
                f'{op} to replica {self.address} timed out '
                f'({timeout_s}s)')
        if not box['ok']:
            raise box['resp']
        return box['resp']

    def forget(self, req_id: int) -> bool:
        """Drop the pending callback; True when it was still pending
        (a response arriving later is silently discarded)."""
        with self._plock:
            return self._pending.pop(req_id, None) is not None

    def _read_loop(self) -> None:
        try:
            while True:
                req_id, ok, payload = recv_frame(self._sock)
                with self._plock:
                    on_done = self._pending.pop(req_id, None)
                if on_done is not None:
                    on_done(ok, payload)
        except (ConnectionError, OSError, EOFError,
                pickle.UnpicklingError) as exc:
            self._fail_all(exc)

    def _fail_all(self, cause) -> None:
        with self._plock:
            if self._lost is not None:
                return
            self._lost = cause
            pending = list(self._pending.items())
            self._pending.clear()
        err = ReplicaLostError(
            f'replica {self.address} lost: {cause}')
        for _req_id, on_done in pending:
            try:
                on_done(False, err)
            except Exception:              # noqa: BLE001
                pass                       # callbacks must not kill IO
        if self._on_lost is not None:
            cb, self._on_lost = self._on_lost, None
            try:
                cb(err)
            except Exception:              # noqa: BLE001
                pass

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._reader.join(timeout=5.0)
