"""Learned bucket catalog: which executables a deployment actually serves.

The service cannot know ahead of time which ``(shape bucket, cfg,
occupancy, shots)`` combinations its traffic produces — but its own
dispatch history does.  :class:`BucketCatalog` persists every bound
:class:`~.bucketspec.BucketSpec` the service dispatches into a small
versioned JSON file; ``ExecutionService(warmup_catalog=...)`` replays
that file at startup on a background thread, AOT-compiling each spec
per device (``sim.interpreter.aot_compile_batch``) so the first real
request of the new process hits warm.  With JAX's persistent
compilation cache enabled the replayed compiles are disk loads, not
XLA runs — the catalog is what turns that cache from "same process
shape reuse" into "warm across deploys".

Write discipline mirrors ``compilecache/store.py``: a magic + version
stamp, tmp-file + ``os.replace`` atomic rewrites (a reader or a crash
never sees a torn file), and a tolerant loader — any parse/version
problem means "empty catalog", never an exception into the serving
path.  The file is small (one dict per distinct bucket; diverse
production traffic is tens of buckets, not thousands) so each record
rewrites the whole file rather than appending.  Because fleet replicas
share ONE catalog file as a warm tier (docs/FLEET.md), every rewrite
happens under an advisory ``flock`` on a ``.lock`` sidecar with a
merge-from-disk first — concurrent recorders compose their entries
instead of last-writer-wins, and a replica's ``begin_run`` replays
what its peers learned, not just its own history.

A catalog left to itself only grows — a retired workload's buckets
would be AOT-recompiled at every startup forever.  So the catalog
ages: each process generation that calls :meth:`begin_run` bumps a
run counter, every dispatch re-stamps its spec's last-seen run, and
``begin_run`` prunes specs not re-observed within ``max_age_runs``
runs plus anything over the ``max_specs`` cap (least-recently-seen
evicted first).  The run/last-seen metadata rides in the same v1
file under keys old readers ignore, so catalogs written by either
side of this change stay mutually loadable.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading

try:
    import fcntl
except ImportError:          # pragma: no cover - non-posix
    fcntl = None

from ..utils import profiling
from .bucketspec import BucketSpec

CATALOG_MAGIC = 'dproc-bucket-catalog'
CATALOG_VERSION = 1


@contextlib.contextmanager
def _file_lock(path: str):
    """Advisory cross-process writer lock on ``path + '.lock'``.

    Fleet replicas share one catalog file (docs/FLEET.md "shared warm
    tiers"); without a lock two concurrent record()s interleave their
    read-modify-rewrite cycles and the later ``os.replace`` silently
    drops the earlier writer's specs.  Best-effort like everything else
    here: if locking is unavailable (non-posix, unwritable dir) the
    body still runs — atomic rename keeps the file un-torn, and a lost
    entry costs one future cold compile, never a request.
    """
    fd = None
    try:
        if fcntl is not None:
            d = os.path.dirname(os.path.abspath(path))
            os.makedirs(d, exist_ok=True)
            fd = os.open(path + '.lock', os.O_CREAT | os.O_RDWR, 0o644)
            fcntl.flock(fd, fcntl.LOCK_EX)
    except OSError:
        if fd is not None:
            os.close(fd)
        fd = None
    try:
        yield
    finally:
        if fd is not None:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)


class BucketCatalog:
    """Durable, deduplicated set of bound BucketSpecs at ``path``.

    Thread-safe; every mutation rewrites the file atomically.  I/O
    errors on record are swallowed after the in-memory set updates —
    losing a catalog entry costs one future cold compile, never a
    request.

    ``max_specs`` caps the catalog size (least-recently-seen specs
    evicted first); ``max_age_runs`` ages out specs not re-observed
    within that many :meth:`begin_run` generations.  Either may be
    None (unbounded / no aging).
    """

    def __init__(self, path: str, max_specs: int = None,
                 max_age_runs: int = None):
        if max_specs is not None and max_specs < 1:
            raise ValueError('max_specs must be >= 1')
        if max_age_runs is not None and max_age_runs < 1:
            raise ValueError('max_age_runs must be >= 1')
        self.path = path
        self.max_specs = max_specs
        self.max_age_runs = max_age_runs
        self._lock = threading.Lock()
        self._specs: dict = {}       # spec.identity() -> spec, ordered
        self._last_seen: dict = {}   # spec.identity() -> run number
        self._run = 0
        self._loaded = False

    # -- read ----------------------------------------------------------

    def load(self) -> list:
        """Specs in insertion order; [] for a missing/corrupt file."""
        with self._lock:
            self._load_locked()
            return list(self._specs.values())

    def begin_run(self) -> list:
        """Open a new process generation: bump the run counter, prune
        aged/over-cap specs, persist, and return the surviving specs
        (the startup warmup replay set).  The service calls this once
        at construction; a catalog opened only via :meth:`load` never
        ages.  Holds the cross-process writer lock around a fresh
        merge-from-disk, so a fleet replica starting up replays specs
        its PEERS recorded, not just its own last generation."""
        with self._lock:
            self._loaded = True
            with _file_lock(self.path):
                self._merge_disk_locked()
                self._run += 1
                self._prune_locked()
                try:
                    self._write_locked()
                except OSError:
                    pass    # durability is best-effort; serving is not
            return list(self._specs.values())

    def _load_locked(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        self._merge_disk_locked()

    def _merge_disk_locked(self) -> None:
        """Fold the on-disk catalog into memory: union of specs, age
        stamps max-merged, run counter max-merged.  A parse/version
        problem merges nothing (in-memory state is never discarded);
        called at first load and — under :func:`_file_lock` — before
        every rewrite, so concurrent replicas' writes compose instead
        of last-writer-wins.  Spec entries are validated ONE AT A
        TIME: a peer that wrote one garbled spec (torn write on a
        non-posix filesystem, a buggy or older writer) costs exactly
        that spec — counted under ``catalog.merge_drops`` — instead of
        aborting the merge and poisoning every replica that shares the
        file."""
        try:
            with open(self.path, 'r', encoding='utf-8') as f:
                doc = json.load(f)
            if doc.get('magic') != CATALOG_MAGIC \
                    or doc.get('version') != CATALOG_VERSION:
                return
            # aging metadata is optional: a file written before the
            # aging change loads with every spec treated as just-seen
            self._run = max(self._run, int(doc.get('runs', 0)))
            last_seen = doc.get('last_seen', {})
            if not isinstance(last_seen, dict):
                last_seen = {}
            dropped = 0
            for d in doc.get('specs', ()):
                try:
                    spec = BucketSpec.from_json(d)
                    ident = spec.identity()
                    seen = int(last_seen.get(self._ident_key(ident),
                                             self._run))
                except (ValueError, TypeError, KeyError,
                        AttributeError):
                    dropped += 1
                    continue
                if ident not in self._specs:
                    self._specs[ident] = spec
                    self._last_seen[ident] = seen
                else:
                    self._last_seen[ident] = max(
                        self._last_seen[ident], seen)
            if dropped:
                profiling.counter_inc('catalog.merge_drops', dropped)
        except (OSError, ValueError, TypeError, KeyError):
            pass

    @staticmethod
    def _ident_key(ident) -> str:
        """JSON object keys must be strings; the identity tuple's repr
        is stable across processes (plain ints/strs/tuples only)."""
        return repr(ident)

    def _prune_locked(self) -> None:
        if self.max_age_runs is not None:
            horizon = self._run - self.max_age_runs
            stale = [i for i, seen in self._last_seen.items()
                     if seen < horizon]
            for ident in stale:
                del self._specs[ident]
                del self._last_seen[ident]
        if self.max_specs is not None \
                and len(self._specs) > self.max_specs:
            # least-recently-seen first; insertion order breaks ties
            order = {i: k for k, i in enumerate(self._specs)}
            victims = sorted(self._specs,
                             key=lambda i: (self._last_seen[i],
                                            order[i]))
            for ident in victims[:len(self._specs) - self.max_specs]:
                del self._specs[ident]
                del self._last_seen[ident]

    # -- write ---------------------------------------------------------

    def record(self, spec: BucketSpec) -> bool:
        """Add one bound spec; False when already present.  The file is
        rewritten atomically on every new spec."""
        if not spec.bound:
            raise ValueError('catalog stores BOUND specs only '
                             '(BucketSpec.bind)')
        with self._lock:
            self._load_locked()
            ident = spec.identity()
            if ident in self._specs:
                # a re-observation refreshes the age stamp in memory;
                # persistence rides the next new-spec or begin_run write
                self._last_seen[ident] = self._run
                return False
            self._specs[ident] = spec
            self._last_seen[ident] = self._run
            with _file_lock(self.path):
                # merge peers' concurrent writes before rewriting, so
                # N replicas recording into one shared catalog never
                # drop each other's entries (two-process contention
                # test in tests/test_fleet.py)
                self._merge_disk_locked()
                self._prune_locked()
                try:
                    self._write_locked()
                except OSError:
                    pass    # durability is best-effort; serving is not
            return True

    def _write_locked(self) -> None:
        doc = {'magic': CATALOG_MAGIC, 'version': CATALOG_VERSION,
               'runs': self._run,
               'last_seen': {self._ident_key(i): seen
                             for i, seen in self._last_seen.items()},
               'specs': [s.to_json() for s in self._specs.values()]}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix='.catalog-',
                                   suffix='.tmp')
        try:
            with os.fdopen(fd, 'w', encoding='utf-8') as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def __len__(self) -> int:
        with self._lock:
            self._load_locked()
            return len(self._specs)
