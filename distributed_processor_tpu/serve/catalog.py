"""Learned bucket catalog: which executables a deployment actually serves.

The service cannot know ahead of time which ``(shape bucket, cfg,
occupancy, shots)`` combinations its traffic produces — but its own
dispatch history does.  :class:`BucketCatalog` persists every bound
:class:`~.bucketspec.BucketSpec` the service dispatches into a small
versioned JSON file; ``ExecutionService(warmup_catalog=...)`` replays
that file at startup on a background thread, AOT-compiling each spec
per device (``sim.interpreter.aot_compile_batch``) so the first real
request of the new process hits warm.  With JAX's persistent
compilation cache enabled the replayed compiles are disk loads, not
XLA runs — the catalog is what turns that cache from "same process
shape reuse" into "warm across deploys".

Write discipline mirrors ``compilecache/store.py``: a magic + version
stamp, tmp-file + ``os.replace`` atomic rewrites (a reader or a crash
never sees a torn file), and a tolerant loader — any parse/version
problem means "empty catalog", never an exception into the serving
path.  The file is small (one dict per distinct bucket; diverse
production traffic is tens of buckets, not thousands) so each record
rewrites the whole file rather than appending.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading

from .bucketspec import BucketSpec

CATALOG_MAGIC = 'dproc-bucket-catalog'
CATALOG_VERSION = 1


class BucketCatalog:
    """Durable, deduplicated set of bound BucketSpecs at ``path``.

    Thread-safe; every mutation rewrites the file atomically.  I/O
    errors on record are swallowed after the in-memory set updates —
    losing a catalog entry costs one future cold compile, never a
    request.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._specs: dict = {}       # spec.identity() -> spec, ordered
        self._loaded = False

    # -- read ----------------------------------------------------------

    def load(self) -> list:
        """Specs in insertion order; [] for a missing/corrupt file."""
        with self._lock:
            self._load_locked()
            return list(self._specs.values())

    def _load_locked(self) -> None:
        if self._loaded:
            return
        self._loaded = True
        try:
            with open(self.path, 'r', encoding='utf-8') as f:
                doc = json.load(f)
            if doc.get('magic') != CATALOG_MAGIC \
                    or doc.get('version') != CATALOG_VERSION:
                return
            for d in doc.get('specs', ()):
                spec = BucketSpec.from_json(d)
                self._specs.setdefault(spec.identity(), spec)
        except (OSError, ValueError, TypeError, KeyError):
            self._specs.clear()

    # -- write ---------------------------------------------------------

    def record(self, spec: BucketSpec) -> bool:
        """Add one bound spec; False when already present.  The file is
        rewritten atomically on every new spec."""
        if not spec.bound:
            raise ValueError('catalog stores BOUND specs only '
                             '(BucketSpec.bind)')
        with self._lock:
            self._load_locked()
            if spec.identity() in self._specs:
                return False
            self._specs[spec.identity()] = spec
            try:
                self._write_locked()
            except OSError:
                pass        # durability is best-effort; serving is not
            return True

    def _write_locked(self) -> None:
        doc = {'magic': CATALOG_MAGIC, 'version': CATALOG_VERSION,
               'specs': [s.to_json() for s in self._specs.values()]}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix='.catalog-',
                                   suffix='.tmp')
        try:
            with os.fdopen(fd, 'w', encoding='utf-8') as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def __len__(self) -> int:
        with self._lock:
            self._load_locked()
            return len(self._specs)
