"""Serve-layer chaos harness: seeded fault injection under the service.

The supervision layer (supervisor thread, circuit breakers, bounded
retries, canary re-admission — docs/ROBUSTNESS.md "serving-layer
failures") is only trustworthy if it is EXERCISED: this module wraps a
live service's ``_run_batch`` with a :class:`ChaosMonkey` that injects
crashes, hangs, slowdowns and dispatcher deaths from a seeded RNG (or
a deterministic script prefix), and a :func:`soak` driver that submits
a stream of requests and asserts the service's whole contract under
fire — every handle terminates (no deadlocks), every completion is
bit-identical to the solo ``simulate_batch`` run, every failure is a
typed error.  The sim-layer analogue is ``sim/fuzz.py`` (PR 4's
fault-injection fuzzer); this is the same discipline one tier up.

Injection sits UNDER the service's retry/breaker machinery and ABOVE
the interpreter, exactly where real infrastructure faults (device
resets, runtime crashes, driver wedges) surface — so canary probes
draw injected faults too, and a quarantined executor only re-admits
once the chaos actually lets a probe through.

One tier further up, :func:`fleet_soak` drives the same contract
against a whole :class:`~.fleet.Fleet`: scripted process-level actions
(SIGKILL, SIGSTOP wedges, SIGCONT) fire at chosen points in the
submission stream while every completion is bit-checked and
timestamped, so the caller can assert not just "nothing hung" but
"goodput stayed positive through the kill window" (docs/FLEET.md).

Used by tests/test_serve_chaos.py, tests/test_fleet.py,
tools/servechaos.py and bench.py's ``availability_under_chaos`` /
``fleet_failover`` rows.
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field

import numpy as np

import jax

from ..sim.interpreter import simulate_batch
from .request import RequestHandle


class ChaosError(RuntimeError):
    """An injected executor crash.  Deliberately a plain RuntimeError:
    :func:`~..sim.interpreter.is_infrastructure_error` classifies it as
    infrastructure, so the retry/breaker path handles it — exactly like
    a real device runtime failure would be."""


class ChaosThreadDeath(BaseException):
    """An injected dispatcher death.  Subclasses BaseException ON
    PURPOSE: it escapes the service's ``except Exception`` batch-failure
    handling and genuinely kills the dispatcher thread, exercising the
    supervisor's dead-thread detection + respawn path."""


# injection outcomes, drawn per _run_batch call
OUTCOMES = ('crash', 'hang', 'slow', 'die', 'corrupt', 'ok')


@dataclass(frozen=True)
class ChaosPlan:
    """What the monkey injects.

    ``script`` is a deterministic prefix of forced outcomes (drawn
    first, in order, regardless of seed) — the way a test guarantees
    "this executor WILL trip its breaker" without depending on RNG
    draws.  After the script is exhausted, outcomes are drawn from the
    seeded RNG with the given probabilities (the remainder is 'ok').
    'hang' sleeps ``hang_s`` then runs the batch anyway — the hung
    dispatch eventually completes as a straggler, which the service
    must discard via the attempt token; 'slow' sleeps ``slow_s``
    (service-time jitter below the watchdog); 'die' raises
    :class:`ChaosThreadDeath` and kills the dispatcher; 'corrupt' runs
    the batch then flips ONE seeded bit in one request's result stats
    — the silent-data-corruption model (docs/ROBUSTNESS.md
    "Integrity"): no exception is raised, so only the audit fabric can
    catch it.
    """
    seed: int = 0
    script: tuple = ()
    p_crash: float = 0.0
    p_hang: float = 0.0
    p_slow: float = 0.0
    p_die: float = 0.0
    p_corrupt: float = 0.0
    hang_s: float = 0.25
    slow_s: float = 0.01

    def __post_init__(self):
        for out in self.script:
            if out not in OUTCOMES:
                raise ValueError(
                    f'script outcome {out!r} not in {OUTCOMES}')
        if self.p_crash + self.p_hang + self.p_slow + self.p_die \
                + self.p_corrupt > 1.0:
            raise ValueError('injection probabilities sum above 1')


class ChaosMonkey:
    """Wraps ``svc._run_batch`` with seeded fault injection.

    All draws happen under one lock so concurrent dispatchers consume
    the script/RNG in a serialized (hence reproducible-per-seed,
    though not per-thread-deterministic) order.  ``injected`` counts
    outcomes actually drawn.  Use as a context manager, or
    ``install()`` / ``uninstall()`` — uninstall restores the original
    bound method, so post-chaos traffic (and shutdown draining) runs
    clean.
    """

    def __init__(self, svc, plan: ChaosPlan):
        self.svc = svc
        self.plan = plan
        self._rng = np.random.default_rng(plan.seed)
        self._lock = threading.Lock()
        self._script = list(plan.script)
        self.injected = collections.Counter()
        self._orig = None
        self._orig_hook = None

    def _draw(self) -> str:
        with self._lock:
            if self._script:
                out = self._script.pop(0)
            else:
                r = float(self._rng.random())
                p = self.plan
                if r < p.p_crash:
                    out = 'crash'
                elif r < p.p_crash + p.p_hang:
                    out = 'hang'
                elif r < p.p_crash + p.p_hang + p.p_slow:
                    out = 'slow'
                elif r < p.p_crash + p.p_hang + p.p_slow + p.p_die:
                    out = 'die'
                elif r < p.p_crash + p.p_hang + p.p_slow + p.p_die \
                        + p.p_corrupt:
                    out = 'corrupt'
                else:
                    out = 'ok'
            self.injected[out] += 1
            return out

    def script_exhausted(self) -> bool:
        with self._lock:
            return not self._script

    def _corrupt_results(self, results):
        """One seeded bit flip in one request's result stats — in the
        first integer stat field, so the corruption always lands in
        bits the tenant would consume (meas/regs/fault words), never
        in a float that might round away.  Raises if the results carry
        no integer array: an injection that cannot corrupt must not be
        counted as one."""
        from ..integrity import flip_bit
        with self._lock:
            ri = int(self._rng.integers(len(results)))
            bit = int(self._rng.integers(0, 16))
            idx = int(self._rng.integers(0, 1 << 16))
        stats = dict(results[ri])
        for k in sorted(stats):
            a = np.asarray(stats[k])
            if a.dtype.kind in 'iu' and a.size:
                stats[k] = flip_bit(a, bit=bit, index=idx)
                break
        else:
            raise ValueError('corrupt injection found no integer '
                             'stat array to flip')
        out = list(results)
        out[ri] = stats
        return out

    def install(self) -> 'ChaosMonkey':
        if self._orig is not None:
            raise RuntimeError('chaos monkey already installed')
        orig = self.svc._run_batch
        plan = self.plan

        def chaotic_run_batch(ex, key, batch, cfg):
            out = self._draw()
            if out != 'ok':
                # the injection lands in the service's flight recorder
                # (and on any traced batch member) so a chaos-soak
                # failure reads as a timeline, not a moved counter
                rec = getattr(self.svc, 'flight_recorder', None)
                if rec is not None:
                    rec.record('chaos_inject', outcome=out,
                               executor=ex.label(), n=len(batch))
                for r in batch:
                    if r.handle._trace is not None:
                        r.handle._trace.instant('chaos', outcome=out,
                                                executor=ex.label())
            if out == 'crash':
                raise ChaosError(
                    f'injected crash on executor {ex.label()}')
            if out == 'die':
                raise ChaosThreadDeath(
                    f'injected dispatcher death on executor '
                    f'{ex.label()}')
            if out == 'hang':
                time.sleep(plan.hang_s)
            elif out == 'slow':
                time.sleep(plan.slow_s)
            elif out == 'corrupt':
                return self._corrupt_results(orig(ex, key, batch, cfg))
            return orig(ex, key, batch, cfg)

        self._orig = orig
        self.svc._run_batch = chaotic_run_batch
        # injected dispatcher deaths are EXPECTED — keep threading's
        # default excepthook from spewing their tracebacks to stderr
        # (anything else still reports through the original hook)
        self._orig_hook = threading.excepthook

        def quiet_hook(args):
            if args.exc_type is ChaosThreadDeath:
                return
            self._orig_hook(args)

        threading.excepthook = quiet_hook
        return self

    def uninstall(self) -> None:
        if self._orig is not None:
            self.svc._run_batch = self._orig
            self._orig = None
        if self._orig_hook is not None:
            threading.excepthook = self._orig_hook
            self._orig_hook = None

    def __enter__(self) -> 'ChaosMonkey':
        return self.install()

    def __exit__(self, *exc_info) -> None:
        self.uninstall()


@dataclass
class SoakReport:
    """What :func:`soak` observed.  The invariants a healthy service
    must hold — ``hung == 0`` (every handle terminated) and
    ``bit_mismatches == 0`` (every completion bit-identical to its
    solo run) — are the caller's asserts; the rest is telemetry."""
    submitted: int = 0
    rejected: int = 0             # typed refusals AT submit
    completed: int = 0
    bit_mismatches: int = 0
    hung: int = 0                 # result() timed out: the bug class
    errors: collections.Counter = field(
        default_factory=collections.Counter)   # typed failures by name
    retries: int = 0              # summed over completed handles
    latencies_s: list = field(default_factory=list)

    def terminated(self) -> int:
        return self.completed + sum(self.errors.values())


def soak(svc, mps, cfg, *, n_requests: int = 100, shots: int = 3,
         seed: int = 0, result_timeout_s: float = 120.0,
         submit_hook=None) -> SoakReport:
    """Submit ``n_requests`` (cycling over ``mps``, seeded random
    measurement bits) and wait every handle out.

    Submission refusals (QueueFullError / OverloadError /
    ServiceClosedError) count as ``rejected``; a handle whose
    ``result(result_timeout_s)`` times out counts as ``hung`` — the
    failure mode the whole supervision layer exists to prevent; other
    failures are tallied by type name.  Every completion is bit-checked
    against the solo ``simulate_batch`` run of the same inputs (one
    reference per program, shared meas bits per program to keep the
    reference count bounded).  ``submit_hook(i)`` runs before each
    submission (pacing, mid-soak shutdown, ...).
    """
    rng = np.random.default_rng(seed)
    bits = {i: rng.integers(0, 2, size=(shots, mp.n_cores,
                                        cfg.max_meas)).astype(np.int32)
            for i, mp in enumerate(mps)}
    refs = {}
    report = SoakReport()
    pending = []
    for i in range(n_requests):
        if submit_hook is not None:
            submit_hook(i)
        pi = i % len(mps)
        t0 = time.monotonic()
        try:
            handle = svc.submit(mps[pi], bits[pi], cfg=cfg)
        except Exception as exc:     # noqa: BLE001 - typed refusal
            report.rejected += 1
            report.errors[type(exc).__name__] += 1
            continue
        report.submitted += 1
        pending.append((pi, handle, t0))
    for pi, handle, t0 in pending:
        assert isinstance(handle, RequestHandle)
        try:
            got = handle.result(timeout=result_timeout_s)
        except TimeoutError:
            report.hung += 1
            continue
        except Exception as exc:     # noqa: BLE001 - typed failure
            report.errors[type(exc).__name__] += 1
            continue
        report.completed += 1
        report.retries += handle.retries
        report.latencies_s.append(time.monotonic() - t0)
        if pi not in refs:
            refs[pi] = jax.tree.map(
                np.asarray, simulate_batch(mps[pi], bits[pi], cfg=cfg))
        want = refs[pi]
        same = set(got) == set(want) and all(
            np.array_equal(np.asarray(got[k]), np.asarray(want[k]))
            for k in want)
        if not same:
            report.bit_mismatches += 1
    return report


@dataclass
class TenantSoakReport(SoakReport):
    """:class:`SoakReport` plus the multi-tenant ledger: ``per_tenant``
    maps tenant → observed ground truth (submitted / completed / shed /
    quota_rejected / shots, counted from the caller's side of every
    handle), and ``meter_mismatches`` lists every disagreement between
    that ground truth and the service's billing meters
    (``stats()['tenants']``).  A healthy service under chaos holds
    ``hung == 0``, ``bit_mismatches == 0`` AND ``meter_mismatches ==
    []`` — injected crashes and retries may neither lose nor
    double-count a tenant's usage (docs/SERVING.md "Tenants")."""
    per_tenant: dict = field(default_factory=dict)
    meter_mismatches: list = field(default_factory=list)


def tenant_soak(svc, mps, cfg, *, tenants, n_requests: int = 100,
                shots: int = 3, seed: int = 0, greedy: str = None,
                greedy_factor: int = 4,
                result_timeout_s: float = 120.0) -> TenantSoakReport:
    """:func:`soak`, with every submission tagged to a tenant and the
    billing meters audited against caller-side ground truth.

    Submissions cycle over ``tenants``; when ``greedy`` names one of
    them, that tenant is scheduled ``greedy_factor`` extra slots per
    cycle — the adversarial shape: one tenant floods admission while
    the others trickle.  Greedy overflow is expected to bounce off its
    own quota (:class:`~.request.QuotaExceededError` counts as
    ``quota_rejected``, not an error); victim requests must complete.

    After every handle terminates, the report's ``meter_mismatches``
    records any tenant whose service-side meters disagree with what
    this driver actually observed: ``completed``, ``shed``,
    ``quota_rejected`` must match exactly, and ``shots`` must equal
    ``completed * shots`` — the exactly-once contract: a chaos retry
    that re-runs a batch may not bill the tenant twice, and a crash
    that loses an attempt may not bill at all.
    """
    tenants = list(tenants)
    if greedy is not None and greedy not in tenants:
        raise ValueError(f'greedy tenant {greedy!r} not in {tenants}')
    cycle = list(tenants)
    if greedy is not None:
        cycle = [greedy] * greedy_factor + \
            [t for t in tenants if t != greedy]
    rng = np.random.default_rng(seed)
    bits = {i: rng.integers(0, 2, size=(shots, mp.n_cores,
                                        cfg.max_meas)).astype(np.int32)
            for i, mp in enumerate(mps)}
    refs = {}
    report = TenantSoakReport()
    zero = dict(submitted=0, completed=0, shed=0, quota_rejected=0,
                shots=0)
    ledger = {t: dict(zero) for t in tenants}
    pending = []
    for i in range(n_requests):
        tenant = cycle[i % len(cycle)]
        pi = i % len(mps)
        t0 = time.monotonic()
        try:
            handle = svc.submit(mps[pi], bits[pi], cfg=cfg,
                                tenant=tenant)
        except Exception as exc:     # noqa: BLE001 - typed refusal
            report.rejected += 1
            report.errors[type(exc).__name__] += 1
            if type(exc).__name__ == 'QuotaExceededError':
                ledger[tenant]['quota_rejected'] += 1
            continue
        report.submitted += 1
        ledger[tenant]['submitted'] += 1
        pending.append((pi, tenant, handle, t0))
    for pi, tenant, handle, t0 in pending:
        assert isinstance(handle, RequestHandle)
        try:
            got = handle.result(timeout=result_timeout_s)
        except TimeoutError:
            report.hung += 1
            continue
        except Exception as exc:     # noqa: BLE001 - typed failure
            report.errors[type(exc).__name__] += 1
            if type(exc).__name__ == 'OverloadError':
                ledger[tenant]['shed'] += 1
            continue
        report.completed += 1
        report.retries += handle.retries
        report.latencies_s.append(time.monotonic() - t0)
        ledger[tenant]['completed'] += 1
        ledger[tenant]['shots'] += shots
        if pi not in refs:
            refs[pi] = jax.tree.map(
                np.asarray, simulate_batch(mps[pi], bits[pi], cfg=cfg))
        want = refs[pi]
        same = set(got) == set(want) and all(
            np.array_equal(np.asarray(got[k]), np.asarray(want[k]))
            for k in want)
        if not same:
            report.bit_mismatches += 1
    report.per_tenant = ledger
    metered = svc.stats().get('tenants', {})
    for t, truth in ledger.items():
        row = metered.get(t)
        if row is None:
            if any(truth.values()):
                report.meter_mismatches.append(
                    f'{t}: no meter row for active tenant')
            continue
        for k in ('completed', 'shed', 'quota_rejected', 'shots'):
            if row.get(k) != truth[k]:
                report.meter_mismatches.append(
                    f'{t}.{k}: metered {row.get(k)} != observed '
                    f'{truth[k]}')
    return report


@dataclass
class FleetSoakReport(SoakReport):
    """:class:`SoakReport` plus the timeline a fleet soak needs:
    ``actions`` records each chaos action as ``(t_rel_s, name, idx)``
    and ``samples`` records each request outcome as ``(t_rel_s,
    'ok' | error-type-name)`` — both relative to soak start — so the
    caller can compute goodput inside any window (e.g. the kill
    window) instead of only end-to-end totals."""
    actions: list = field(default_factory=list)
    samples: list = field(default_factory=list)

    def goodput(self, t0: float = 0.0, t1: float = None) -> float:
        """Completed-OK requests per second inside ``[t0, t1]``
        (relative seconds; ``t1`` defaults to the last sample)."""
        if t1 is None:
            t1 = max((t for t, _ in self.samples), default=0.0)
        n = sum(1 for t, out in self.samples
                if t0 <= t <= t1 and out == 'ok')
        return n / max(t1 - t0, 1e-9)

    def ok_in_window(self, t0: float, t1: float) -> int:
        return sum(1 for t, out in self.samples
                   if t0 <= t <= t1 and out == 'ok')


def fleet_soak(fleet, mps, cfg, *, n_requests: int = 100,
               shots: int = 3, seed: int = 0, rate_hz: float = None,
               actions=(), result_timeout_s: float = 120.0
               ) -> FleetSoakReport:
    """:func:`soak`, against a :class:`~.fleet.Fleet`, with scripted
    process-level chaos.

    ``actions`` is a sequence of ``(at_request_index, method, idx)``
    triples — ``method`` is a Fleet chaos hook name (``'kill'``,
    ``'wedge'``, ``'unwedge'``) applied to replica ``idx`` just before
    submission ``at_request_index``; each firing is timestamped into
    the report.  ``idx = -1`` resolves AT FIRE TIME to the router's
    :meth:`~.router.FleetRouter.primary_replica` (the one carrying the
    load), so a scripted kill always lands on the serving path even
    when bucket affinity pinned the whole workload to one home — and
    an ``unwedge -1`` re-targets whatever the last ``wedge`` hit.
    ``rate_hz`` paces submissions (None = as fast as possible).
    Completions are timestamped by polling ``done()`` so the report's
    ``samples`` reflect when each handle actually resolved, not the
    order the caller happened to wait in.

    The fleet contract under fire, assertable from the report:
    ``hung == 0``, ``bit_mismatches == 0``, every non-completion a
    typed error, and ``ok_in_window(kill_t, kill_t + w) > 0`` —
    serving never stops while a replica is down.
    """
    rng = np.random.default_rng(seed)
    bits = {i: rng.integers(0, 2, size=(shots, mp.n_cores,
                                        cfg.max_meas)).astype(np.int32)
            for i, mp in enumerate(mps)}
    refs = {}
    report = FleetSoakReport()
    start = time.monotonic()
    script = sorted(actions, key=lambda a: a[0])
    ai = 0
    resolved = {}                # method -> last concrete replica idx

    def fire(method, idx):
        if idx == -1:
            if method == 'unwedge' and 'wedge' in resolved:
                idx = resolved['wedge']
            else:
                rid = fleet.router.primary_replica()
                rids = fleet.replica_ids()
                idx = rids.index(rid) if rid in rids else 0
        resolved[method] = idx
        getattr(fleet, method)(idx)
        report.actions.append(
            (round(time.monotonic() - start, 4), method, idx))

    pending = {}                 # handle -> (program idx, submit time)
    for i in range(n_requests):
        while ai < len(script) and script[ai][0] <= i:
            _, method, idx = script[ai]
            ai += 1
            fire(method, idx)
        if rate_hz:
            time.sleep(1.0 / rate_hz)
        pi = i % len(mps)
        t0 = time.monotonic()
        try:
            handle = fleet.submit(mps[pi], bits[pi], cfg=cfg)
        except Exception as exc:     # noqa: BLE001 - typed refusal
            report.rejected += 1
            report.errors[type(exc).__name__] += 1
            report.samples.append((round(t0 - start, 4),
                                   type(exc).__name__))
            continue
        report.submitted += 1
        pending[handle] = (pi, t0)
    for _, method, idx in script[ai:]:   # actions past the last submit
        fire(method, idx)
    deadline = time.monotonic() + result_timeout_s
    while pending and time.monotonic() < deadline:
        for handle in [h for h in pending if h.done()]:
            pi, t0 = pending.pop(handle)
            t_rel = round(time.monotonic() - start, 4)
            exc = handle.exception(timeout=0)
            if exc is not None:
                report.errors[type(exc).__name__] += 1
                report.samples.append((t_rel, type(exc).__name__))
                continue
            got = handle.result(timeout=0)
            report.completed += 1
            report.latencies_s.append(time.monotonic() - t0)
            report.samples.append((t_rel, 'ok'))
            if pi not in refs:
                refs[pi] = jax.tree.map(
                    np.asarray,
                    simulate_batch(mps[pi], bits[pi], cfg=cfg))
            want = refs[pi]
            same = set(got) == set(want) and all(
                np.array_equal(np.asarray(got[k]), np.asarray(want[k]))
                for k in want)
            if not same:
                report.bit_mismatches += 1
        time.sleep(0.005)
    report.hung += len(pending)
    return report
