"""BucketSpec: the serving tier's executable identity, as a value.

The coalescer, warmup, the benches, and the catalog all need to talk
about "which compiled executable would this request dispatch into?".
Before this module that identity was an ad-hoc tuple only
``batcher.bucket_key`` produced and only the Coalescer consumed; AOT
precompilation (docs/SERVING.md "cold start & warmup") needs the same
identity to be

* **hashable/comparable** — it is the coalescing dict key and the AOT
  cache key;
* **serializable** — the learned bucket catalog (`serve/catalog.py`)
  persists it as JSON and replays it in a different process;
* **bindable** — the coalescing key deliberately excludes batch
  occupancy and shot count (short requests pad up), but an XLA
  executable is shape-exact, so warmup *binds* the template to concrete
  ``(n_programs, n_shots)`` before compiling.

A spec is a frozen dataclass in two states: the **unbound template**
(``n_programs``/``n_shots`` are None) is what ``bucket_key`` returns
and what buckets coalesce under; :meth:`bind` produces the **bound**
spec that names one exact executable, which is what
``sim.interpreter.aot_compile_batch`` compiles and the catalog stores.

``traits`` (the :func:`~..sim.interpreter.program_traits` static jit
argument) rides along for AOT exactness but is deliberately excluded
from equality/hash (``compare=False``): the coalescing contract lets
programs with different instruction mixes share a batch (the stacked
dispatch uses the trait UNION over members, the ensemble semantics
``_run_multi_batch_jit`` documents), and keying coalescing on traits
would silently split such batches.  The AOT cache and the catalog key
on ``traits`` explicitly where the exact executable matters.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field, replace

from .. import isa
from ..sim.interpreter import InterpreterConfig, program_traits

# bump when the JSON layout changes; loaders reject other versions
SPEC_VERSION = 1

# InterpreterConfig fields that arrive from JSON as lists but must be
# tuples to restore hashability
_CFG_TUPLE_FIELDS = ('lut_mask', 'lut_table')


@dataclass(frozen=True)
class BucketSpec:
    """One serving bucket's executable identity.

    ``geometry`` is per-core nested — ``((samples_per_clk,
    interp_ratio), ...)`` per element table — so the stacked
    ``[n_cores, max_elems]`` constant shapes are reconstructible from
    the spec alone (the old flat tuple lost the per-core grouping).
    """
    n_cores: int
    n_instr_bucket: int
    geometry: tuple                    # per core: ((spc, interp), ...)
    cfg: InterpreterConfig             # normalized (static jit arg)
    # program_traits(): (kinds, b, b) — informational for coalescing
    # (compare=False, see module docstring), exact for AOT/catalog
    traits: tuple = field(default=None, compare=False)
    # binding: None = unbound coalescing template
    n_programs: int = None             # padded program-axis occupancy
    n_shots: int = None                # padded shot count
    has_init_regs: bool = False

    # -- construction --------------------------------------------------

    @classmethod
    def from_program(cls, mp, cfg: InterpreterConfig) -> 'BucketSpec':
        """Unbound template for one machine program under ``cfg``
        (``cfg`` must already be jit-normalized — the service's
        ``_normalize_cfg`` output)."""
        geom = tuple(tuple((int(ec.samples_per_clk), int(ec.interp_ratio))
                           for ec in t.elem_cfgs) for t in mp.tables)
        return cls(int(mp.n_cores), int(isa.shape_bucket(mp.n_instr)),
                   geom, cfg, program_traits(mp))

    def bind(self, *, n_programs: int, n_shots: int,
             has_init_regs: bool = False) -> 'BucketSpec':
        """The bound spec naming one exact executable."""
        return replace(self, n_programs=int(n_programs),
                       n_shots=int(n_shots),
                       has_init_regs=bool(has_init_regs))

    def template(self) -> 'BucketSpec':
        """Back to the unbound coalescing key."""
        if self.n_programs is None and self.n_shots is None \
                and not self.has_init_regs:
            return self
        return replace(self, n_programs=None, n_shots=None,
                       has_init_regs=False)

    # -- derived views -------------------------------------------------

    @property
    def bound(self) -> bool:
        return self.n_programs is not None and self.n_shots is not None

    @property
    def max_elems(self) -> int:
        """Element axis of the stacked per-core constant tables."""
        return max((len(g) for g in self.geometry), default=0) or 1

    def label(self) -> str:
        """Human/stats label; bound specs carry their occupancy."""
        s = f'c{self.n_cores}i{self.n_instr_bucket}'
        if self.bound:
            s += f'p{self.n_programs}s{self.n_shots}'
        return s

    def identity(self) -> tuple:
        """Exact executable identity: spec equality PLUS traits (which
        ``__eq__`` deliberately ignores) — the dedup key wherever the
        precise compiled artifact matters (catalog entries, the
        service's recorded-spec set)."""
        return (self, self.traits)

    def shape_sig(self) -> tuple:
        """The dispatch-shape signature the service's cold/warm
        classifier records — must mirror ``_run_batch``'s
        ``('multi', P, B, init is None)``."""
        return ('multi', self.n_programs, self.n_shots,
                not self.has_init_regs)

    # -- JSON ----------------------------------------------------------

    def to_json(self) -> dict:
        kinds, in0_reg, p_regsel = (self.traits if self.traits is not None
                                    else (None, None, None))
        return {
            'version': SPEC_VERSION,
            'n_cores': self.n_cores,
            'n_instr_bucket': self.n_instr_bucket,
            'geometry': [[list(pair) for pair in core]
                         for core in self.geometry],
            'cfg': asdict(self.cfg),
            'traits': None if self.traits is None else
                [sorted(int(k) for k in kinds), bool(in0_reg),
                 bool(p_regsel)],
            'n_programs': self.n_programs,
            'n_shots': self.n_shots,
            'has_init_regs': self.has_init_regs,
        }

    @classmethod
    def from_json(cls, d: dict) -> 'BucketSpec':
        if d.get('version') != SPEC_VERSION:
            raise ValueError(f'BucketSpec version {d.get("version")!r} '
                             f'!= {SPEC_VERSION}')
        cfg_d = dict(d['cfg'])
        for k in _CFG_TUPLE_FIELDS:
            if k in cfg_d and cfg_d[k] is not None:
                cfg_d[k] = tuple(cfg_d[k])
        cfg = InterpreterConfig(**cfg_d)
        traits = d.get('traits')
        if traits is not None:
            traits = (frozenset(int(k) for k in traits[0]),
                      bool(traits[1]), bool(traits[2]))
        geom = tuple(tuple(tuple(int(x) for x in pair) for pair in core)
                     for core in d['geometry'])
        np_, ns = d.get('n_programs'), d.get('n_shots')
        return cls(int(d['n_cores']), int(d['n_instr_bucket']), geom,
                   cfg, traits,
                   None if np_ is None else int(np_),
                   None if ns is None else int(ns),
                   bool(d.get('has_init_regs', False)))
