"""Continuous-batching execution service (docs/SERVING.md).

The serving tier sits above the multi-program interpreter: many
independent callers submit compiled machine programs asynchronously;
dispatchers coalesce them into shape-bucketed batches so they share
``simulate_multi_batch``'s warm jit cache, then demux per-request
stats back onto future-like handles.  With ``devices=`` the service
shards into a pool of per-device executors — bucket-affinity routing
keeps each bucket's warm cache hot on its home device, work stealing
moves ripened batches to idle devices — scaling one host's serving
throughput across its whole device mesh.  The QubiC reference serves
one FPGA board per user; the TPU port serves many users per chip (and
many chips per service) by making batch occupancy and device placement
scheduling decisions instead of caller obligations.

The service is self-healing (docs/ROBUSTNESS.md "serving-layer
failures"): a supervisor thread health-checks every executor
(heartbeat, hang watchdog, dead-thread detection), a per-executor
circuit breaker quarantines repeat infrastructure offenders and
re-admits them through bit-checked canary probes, infrastructure
failures retry on healthy executors under a bounded
:class:`RetryPolicy`, and overload control (``max_est_wait_ms``)
sheds or rejects work with :class:`OverloadError` instead of letting
queues grow into missed deadlines.  ``serve.chaos`` injects seeded
crashes/hangs/slowdowns under ``_run_batch`` to prove all of it.

Shared capacity is tenant-fair (docs/SERVING.md "Tenants"): every
submission carries a tenant identity end-to-end, the coalescer runs
weighted deficit round-robin across tenants, admission enforces
per-tenant quotas with the typed, non-retryable
:class:`QuotaExceededError`, usage is metered exactly-once into
billing-grade ``tenant.*`` counters, and per-tenant SLO budgets close
the loop through the Fleet's :class:`AutoscalePolicy`.
"""

from ..integrity import IntegrityError
from .batcher import Coalescer, bucket_key
from .bucketspec import BucketSpec
from .catalog import BucketCatalog
from .chaos import (ChaosError, ChaosMonkey, ChaosPlan,
                    ChaosThreadDeath, FleetSoakReport, SoakReport,
                    TenantSoakReport, fleet_soak, soak, tenant_soak)
from .fleet import AutoscalePolicy, Fleet
from .request import (CancelledError, DeadlineError, ExecutorLostError,
                      OverloadError, QueueFullError,
                      QuotaExceededError, RequestHandle,
                      ServiceClosedError, ShutdownError)
from .router import (ROUTER_THREAD_PREFIX, FleetRouter,
                     is_terminal_error)
from .service import (CANARY_THREAD_PREFIX, DISPATCH_THREAD_PREFIX,
                      SUPERVISE_THREAD_PREFIX, WARMUP_THREAD_PREFIX,
                      ExecutionService)
from .stream import StreamKey, StreamSession
from .supervise import (HEALTH_LIVE, HEALTH_PROBING,
                        HEALTH_QUARANTINED, CircuitBreaker, RetryPolicy)
from .transport import (WIRE_THREAD_PREFIX, ReplicaClient,
                        ReplicaLostError, ReplicaServer,
                        WireCorruptionError)

__all__ = [
    'AutoscalePolicy',
    'BucketCatalog',
    'BucketSpec',
    'CANARY_THREAD_PREFIX',
    'CancelledError',
    'ChaosError',
    'ChaosMonkey',
    'ChaosPlan',
    'ChaosThreadDeath',
    'CircuitBreaker',
    'Coalescer',
    'DISPATCH_THREAD_PREFIX',
    'DeadlineError',
    'ExecutionService',
    'ExecutorLostError',
    'Fleet',
    'FleetRouter',
    'FleetSoakReport',
    'HEALTH_LIVE',
    'HEALTH_PROBING',
    'HEALTH_QUARANTINED',
    'IntegrityError',
    'OverloadError',
    'QueueFullError',
    'QuotaExceededError',
    'ROUTER_THREAD_PREFIX',
    'ReplicaClient',
    'ReplicaLostError',
    'ReplicaServer',
    'RequestHandle',
    'RetryPolicy',
    'SUPERVISE_THREAD_PREFIX',
    'ServiceClosedError',
    'ShutdownError',
    'SoakReport',
    'StreamKey',
    'StreamSession',
    'TenantSoakReport',
    'WARMUP_THREAD_PREFIX',
    'WIRE_THREAD_PREFIX',
    'WireCorruptionError',
    'bucket_key',
    'fleet_soak',
    'is_terminal_error',
    'soak',
    'tenant_soak',
]
