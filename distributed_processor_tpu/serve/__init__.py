"""Continuous-batching execution service (docs/SERVING.md).

The serving tier sits above the multi-program interpreter: many
independent callers submit compiled machine programs asynchronously;
one dispatcher coalesces them into shape-bucketed batches so they share
``simulate_multi_batch``'s warm jit cache, then demuxes per-request
stats back onto future-like handles.  The QubiC reference serves one
FPGA board per user; the TPU port serves many users per chip by making
batch occupancy a scheduling decision instead of a caller obligation.
"""

from .batcher import Coalescer, bucket_key
from .request import (CancelledError, DeadlineError, QueueFullError,
                      RequestHandle, ServiceClosedError)
from .service import DISPATCH_THREAD_PREFIX, ExecutionService

__all__ = [
    'CancelledError',
    'Coalescer',
    'DISPATCH_THREAD_PREFIX',
    'DeadlineError',
    'ExecutionService',
    'QueueFullError',
    'RequestHandle',
    'ServiceClosedError',
    'bucket_key',
]
