"""Continuous-batching execution service (docs/SERVING.md).

The serving tier sits above the multi-program interpreter: many
independent callers submit compiled machine programs asynchronously;
dispatchers coalesce them into shape-bucketed batches so they share
``simulate_multi_batch``'s warm jit cache, then demux per-request
stats back onto future-like handles.  With ``devices=`` the service
shards into a pool of per-device executors — bucket-affinity routing
keeps each bucket's warm cache hot on its home device, work stealing
moves ripened batches to idle devices — scaling one host's serving
throughput across its whole device mesh.  The QubiC reference serves
one FPGA board per user; the TPU port serves many users per chip (and
many chips per service) by making batch occupancy and device placement
scheduling decisions instead of caller obligations.
"""

from .batcher import Coalescer, bucket_key
from .request import (CancelledError, DeadlineError, QueueFullError,
                      RequestHandle, ServiceClosedError)
from .service import DISPATCH_THREAD_PREFIX, ExecutionService

__all__ = [
    'CancelledError',
    'Coalescer',
    'DISPATCH_THREAD_PREFIX',
    'DeadlineError',
    'ExecutionService',
    'QueueFullError',
    'RequestHandle',
    'ServiceClosedError',
    'bucket_key',
]
