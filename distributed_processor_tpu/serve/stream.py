"""Streaming traffic class: long-lived QEC round sessions.

The serving counterpart of :func:`~..sim.interpreter.simulate_rounds`
(docs/SERVING.md "Streaming sessions"): a :class:`StreamSession` is a
long-lived handle over one program whose round chunks dispatch as
device-resident ``lax.scan`` executions — R rounds plus the in-loop
decode per dispatch — instead of R one-shot submissions each paying the
per-call floor (docs/PERF.md "Streaming QEC").

Round chunks ride the ORDINARY request lifecycle: each
``submit_rounds`` is one :class:`~.request.Request` with ``rounds``
set, so deadlines (honored at scan-chunk boundaries), retry/steal
under the attempt-token machinery, priority lanes, and overload
control all apply unchanged — a chaos kill of the home executor
retries the chunk elsewhere with a fresh token and the stale dispatch
cannot double-complete it (no lost or duplicated round results).
Stickiness comes from the routing key: every chunk of a session
shares one :class:`StreamKey`, so the bucket-affinity router pins the
whole session to a home executor and its warm scan executable.

``StreamSession`` is generic over its target: the in-process
:class:`~.service.ExecutionService` and the fleet
:class:`~.router.FleetRouter` both expose ``submit_rounds`` /
``close_stream``, so a session streams over the PR 12 wire protocol
unchanged — each chunk's result is one incremental frame.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class StreamKey:
    """Routing/coalescing key for one stream session's chunks.

    Unlike the shape-keyed :class:`~.bucketspec.BucketSpec`, a stream
    key is SESSION-keyed: every chunk of session ``sid`` hashes to the
    same key regardless of its round count, so the affinity router
    pins the whole session to one home executor (chunks of different
    round counts still share the home; the scan executable itself keys
    on ``cfg.rounds`` inside the jit cache).  Carries the same
    attribute surface the service's bucket bookkeeping touches
    (``n_cores`` / ``n_instr_bucket`` / ``cfg`` / ``label()``)."""
    sid: int
    n_cores: int
    n_instr_bucket: int
    cfg: object

    def label(self) -> str:
        return f'stream{self.sid}c{self.n_cores}i{self.n_instr_bucket}'


class StreamSession:
    """One open stream: submit round chunks, read incremental results.

    Not thread-safe for concurrent ``submit_rounds`` calls (one
    producer per session — the hardware analogue is one readout
    stream); results may be consumed from another thread.

    ``submit_rounds(meas_bits)`` takes ``[rounds, n_shots, n_cores,
    n_meas]`` and returns the chunk's
    :class:`~.request.RequestHandle` immediately; :meth:`results`
    yields completed chunk results in submission order (each the
    :func:`~..sim.interpreter.simulate_rounds` pytree — leading round
    axis per leaf, plus ``syndrome_hist``/``decoded`` when the session
    decodes).  :meth:`close` drains outstanding chunks, deregisters
    the session, and returns a summary — including the full-history
    decode over every chunk's syndrome when a decode spec is bound.
    """

    def __init__(self, target, mp, sid: int, *, cfg=None, decode=None,
                 round_deadline_ms: float = None, priority: int = 0,
                 fault_mode: str = None, tenant: str = None):
        self._target = target
        self.mp = mp
        self.sid = sid
        self.cfg = cfg
        self.decode = decode
        self.round_deadline_ms = round_deadline_ms
        self.priority = priority
        self.fault_mode = fault_mode
        # tenant identity is a SESSION property: every chunk inherits
        # it (docs/SERVING.md "Tenants"), so a stream's rounds are
        # metered and fair-queued under the tenant that opened it
        self.tenant = tenant
        self._chunks = []          # (rounds, handle) in submit order
        self._yielded = 0
        self._closed = False

    # -- producer side ---------------------------------------------------

    def submit_rounds(self, meas_bits, init_regs=None):
        """Queue one R-round chunk; returns its handle immediately.
        The chunk deadline (when the session has a per-round deadline)
        is ``rounds * round_deadline_ms`` — deadlines are honored at
        scan-chunk boundaries, the scan itself is uninterruptible."""
        if self._closed:
            raise RuntimeError(f'stream {self.sid} is closed')
        meas_bits = np.asarray(meas_bits, np.int32)
        handle = self._target.submit_rounds(
            self.mp, meas_bits, init_regs=init_regs, cfg=self.cfg,
            decode=self.decode, priority=self.priority,
            round_deadline_ms=self.round_deadline_ms,
            fault_mode=self.fault_mode, stream=self.sid,
            tenant=self.tenant)
        self._chunks.append((int(meas_bits.shape[0]), handle))
        return handle

    # -- consumer side ---------------------------------------------------

    @property
    def rounds_submitted(self) -> int:
        return sum(r for r, _ in self._chunks)

    def results(self, timeout: float = None):
        """Yield chunk results not yet consumed, in submission order
        (the incremental round-result frames).  Blocks up to
        ``timeout`` seconds PER CHUNK; a failed chunk re-raises its
        typed error here, exactly like ``handle.result()``."""
        while self._yielded < len(self._chunks):
            _, handle = self._chunks[self._yielded]
            res = handle.result(timeout)
            self._yielded += 1
            yield res

    def close(self, timeout: float = None) -> dict:
        """Drain every outstanding chunk, deregister the session with
        the target, and return the session summary: chunk/round
        counts, per-chunk fault words... and, when the session binds a
        decode spec, the FULL-history decode — every chunk's syndrome
        history concatenated along the round axis and decoded once
        (the streaming equivalent of one giant ``simulate_rounds``
        decode)."""
        if self._closed:
            raise RuntimeError(f'stream {self.sid} is already closed')
        errors = []
        results = []
        for _, handle in self._chunks:
            try:
                results.append(handle.result(timeout))
            except Exception as exc:   # noqa: BLE001 - summarize, re-raise typed
                errors.append(exc)
        self._closed = True
        self._target.close_stream(self.sid)
        summary = {
            'sid': self.sid,
            'chunks': len(self._chunks),
            'rounds': self.rounds_submitted,
            'failed_chunks': len(errors),
            'errors': errors,
        }
        hists = [np.asarray(r['syndrome_hist']) for r in results
                 if 'syndrome_hist' in r]
        if hists and self.decode is not None:
            from ..ops.decode import as_decode_spec, decode_history
            hist = np.concatenate(hists, axis=1)   # [B, R_total, K]
            summary['syndrome_hist'] = hist
            summary['decoded'] = np.asarray(decode_history(
                hist, as_decode_spec(self.decode).scheme))
        return summary

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        if not self._closed:
            self.close()
