"""Request lifecycle for the execution service: the future-like handle.

A submission is decoupled from its execution: :meth:`ExecutionService.
submit` returns a :class:`RequestHandle` immediately and the dispatcher
thread fulfils it after the request rides a coalesced batch through the
interpreter.  The handle is the only object a submitter touches, so its
state machine is deliberately small and fully lock-guarded:

``queued``      in the coalescer, cancellable, deadline armed
``dispatched``  claimed by the dispatcher for the batch being built —
                cancellation no longer possible (the batch boundary IS
                the cancellation point)
``done``        result or exception set, ``result()`` unblocked

Every transition happens under the handle's own lock, so ``cancel()``
racing the dispatcher's claim has exactly one winner.  ``dispatched``
can move BACK to ``queued`` exactly one way: the supervision layer
re-queues a request whose batch died on executor infrastructure (crash,
hang, dead dispatcher — docs/ROBUSTNESS.md "serving-layer failures").
Each claim hands the dispatcher an **attempt token**; fulfilling or
failing with a stale token is a silent no-op, so a hung dispatch that
eventually returns after its request was retried elsewhere cannot
double-complete the handle, and ``cancel()`` racing a retry re-queue
still has exactly one winner (the re-queue invalidates the old token,
the cancel flips the state to done, the next claim loses).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class QueueFullError(RuntimeError):
    """Admission control: the service's bounded queue is full.

    Raised by ``submit`` (never stored on a handle) — backpressure is
    synchronous so the caller can shed load or retry, instead of the
    queue growing without bound under overload.
    """


class CancelledError(RuntimeError):
    """The request was cancelled (``handle.cancel()`` or a non-draining
    shutdown) before it was dispatched."""


class ShutdownError(CancelledError):
    """The service shut down before this request could run.

    Raised on handles still queued at ``shutdown(drain=False)`` and on
    any handle left unresolved when the last dispatcher exits — a
    forced shutdown must fail every outstanding handle so ``result()``
    can never block forever.  Subclasses :class:`CancelledError`: a
    shutdown IS a service-initiated cancellation, just a typed one.
    """


class OverloadError(RuntimeError):
    """Admission-control shed: the service refused (or evicted) this
    request because the estimated queue service time exceeds the
    configured bound (``max_est_wait_ms``) or provably exceeds the
    request's own ``deadline_ms``.  Shedding early and loudly beats
    queueing a request that can only expire — the caller can back off,
    retry elsewhere, or lower its demands (docs/SERVING.md
    "overload control")."""


class QuotaExceededError(ValueError):
    """Admission control: THIS TENANT is over one of its configured
    limits (max queued requests, shots/s, or compile-submissions/s —
    docs/SERVING.md "Tenants").

    Distinct from :class:`OverloadError` on purpose: an overload shed
    says "the service is busy, back off and retry" while a quota
    rejection says "your contract forbids this rate, retrying verbatim
    cannot succeed".  Subclasses :class:`ValueError` so the fault
    taxonomy (``is_infrastructure_error``) classifies it program-side:
    the retry/failover machinery surfaces it to the caller immediately
    instead of burning attempts on other replicas.
    """


class ExecutorLostError(RuntimeError):
    """The executor running this request's batch was lost (dispatcher
    thread died, or a dispatch hung past the watchdog) and the retry
    budget could not place it elsewhere.  Infrastructure-class: the
    supervision layer retries these under the service's
    :class:`~.supervise.RetryPolicy` before they ever surface."""


class DeadlineError(RuntimeError):
    """The request's deadline passed before a batch picked it up.

    Deadlines are honored at BATCH BOUNDARIES: a request already
    claimed for a batch runs to completion (the interpreter cannot be
    interrupted mid-dispatch); one still queued when its deadline
    expires is failed with this error at the next dispatch opportunity.
    """


class ServiceClosedError(RuntimeError):
    """``submit`` after ``shutdown`` began."""


_QUEUED, _DISPATCHED, _DONE = 'queued', 'dispatched', 'done'

# the tenant every unattributed submission is normalized onto at the
# admission boundary — single-tenant deployments never name a tenant
# and simply ARE the default tenant (docs/SERVING.md "Tenants")
DEFAULT_TENANT = 'default'


class RequestHandle:
    """Future-like handle for one submitted program.

    ``result(timeout)`` blocks for the per-request stats dict (the
    exact :func:`~...sim.interpreter.simulate_batch` schema, demuxed
    from the coalesced batch), re-raising the request's failure —
    :class:`~...sim.interpreter.FaultError` under strict fault mode,
    :class:`CancelledError`, :class:`DeadlineError` — and raising
    :class:`TimeoutError` if nothing arrived within ``timeout``
    seconds (the request itself stays live).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._state = _QUEUED
        self._result = None
        self._exception = None
        # attempt token: bumped by every _claim and every _requeue, so
        # an executor holding a stale token (its dispatch hung or
        # failed and the request was retried elsewhere) cannot
        # complete the handle
        self._attempt = 0
        # supervision counters (written under _lock by the service):
        # how many times the request was re-queued after an
        # infrastructure failure / migrated between executor queues
        self.retries = 0
        self.migrations = 0
        # observability context slot: None (untraced — the whole cost
        # of the tracing-off path) or the obs.trace.TraceContext the
        # serving layers append lifecycle spans to
        self._trace = None
        # exactly-once resolution hook: the service installs a callback
        # at admission and it fires on the SINGLE winning transition to
        # done — including the submitter-side cancel() path that never
        # re-enters the service — so per-tenant outstanding counts can
        # never drift.  Called as cb(ok: bool) outside the handle lock.
        self._on_done = None

    # -- submitter side -------------------------------------------------

    def result(self, timeout: float = None) -> dict:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f'request not completed within {timeout!r} s '
                f'(still {self._state})')
        if self._exception is not None:
            raise self._exception
        return self._result

    def exception(self, timeout: float = None):
        """The stored failure (or None), same blocking as ``result``."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f'request not completed within {timeout!r} s')
        return self._exception

    def done(self) -> bool:
        return self._event.is_set()

    def cancelled(self) -> bool:
        return isinstance(self._exception, CancelledError)

    def trace(self) -> list | None:
        """Recorded lifecycle spans (docs/OBSERVABILITY.md), or None
        when this request was not sampled for tracing.  Each span is a
        dict ``{name, t0, t1, args}`` with monotonic-clock seconds and
        ``t1 is None`` for instant hop events; the list is a snapshot
        and safe to mutate."""
        ctx = self._trace
        return None if ctx is None else list(ctx.spans)

    def cancel(self) -> bool:
        """Cancel if still queued.  Returns True when this call won —
        the request will never execute and ``result()`` raises
        :class:`CancelledError`.  Returns False when the request was
        already claimed by a batch or already done: past the batch
        boundary there is nothing left to cancel."""
        return self._fail(CancelledError('request cancelled'),
                          only_queued=True)

    # -- service side ---------------------------------------------------

    def _set_on_done(self, cb) -> bool:
        """Install the exactly-once resolution callback.  Returns False
        — NOT installed — when the handle already resolved (e.g. a
        submit_source handle cancelled during its compile), so the
        installer knows its accounting will never be balanced by the
        callback and must not open it."""
        with self._lock:
            if self._state == _DONE:
                return False
            self._on_done = cb
            return True

    def _claim(self):
        """Dispatcher: move queued -> dispatched.  Returns the attempt
        token (a truthy int) the claimer must present to ``_fulfill``/
        ``_fail``/``_requeue``, or 0 if the request was cancelled or
        failed first (the batch must skip it)."""
        with self._lock:
            if self._state != _QUEUED:
                return 0
            self._state = _DISPATCHED
            self._attempt += 1
            return self._attempt

    def _requeue(self, token: int) -> bool:
        """Supervision: move dispatched -> queued for a retry after an
        infrastructure failure.  Invalidates ``token`` (a straggling
        duplicate of the failed dispatch can no longer complete the
        handle) and bumps ``retries``.  False when the handle is
        already done (cancel/deadline won) or the token is stale (a
        different retry already happened)."""
        with self._lock:
            if self._state != _DISPATCHED or token != self._attempt:
                return False
            self._state = _QUEUED
            self._attempt += 1
            self.retries += 1
        if self._trace is not None:
            self._trace.instant('requeue', attempt=self.retries)
        return True

    def _fulfill(self, result: dict, token: int = None) -> bool:
        with self._lock:
            if self._state == _DONE:
                return False
            if token is not None and token != self._attempt:
                return False        # stale dispatch: retried elsewhere
            self._state = _DONE
            self._result = result
        if self._trace is not None:
            self._trace.instant('done', outcome='ok')
        self._event.set()
        self._notify_done(True)
        return True

    def _fail(self, exc: BaseException, only_queued: bool = False,
              token: int = None) -> bool:
        with self._lock:
            if self._state == _DONE or \
                    (only_queued and self._state != _QUEUED):
                return False
            if token is not None and token != self._attempt:
                return False        # stale dispatch: retried elsewhere
            self._state = _DONE
            self._exception = exc
        if self._trace is not None:
            self._trace.instant('done', outcome=type(exc).__name__)
        self._event.set()
        self._notify_done(False)
        return True

    def _notify_done(self, ok: bool) -> None:
        # pop-then-call: the slot is cleared before invocation so even
        # a re-entrant resolution attempt from inside the callback
        # cannot fire it twice
        cb, self._on_done = self._on_done, None
        if cb is not None:
            try:
                cb(ok)
            except Exception:
                pass        # accounting must never poison resolution


@dataclass
class Request:
    """One normalized submission, as the batcher sees it.

    ``meas_bits`` / ``init_regs`` are already validated and in their
    full per-shot forms (``[n_shots, n_cores, n_meas]`` /
    ``[n_shots, n_cores, N_REGS]`` or None); ``cfg`` is the normalized
    count-mode :class:`InterpreterConfig` that is part of the bucket
    key; ``strict`` records whether THIS request (not its batch-mates)
    wants ``FaultError`` on trapped shots.  ``deadline`` is an absolute
    ``time.monotonic()`` value or None; ``seq`` is the service-wide
    arrival number used as the FIFO tiebreak inside a priority lane.
    ``migrations`` counts how many times work stealing moved this
    request between per-device queues (each hop re-runs the
    deadline/cancel checks at the re-queue boundary; mirrored onto the
    handle).  ``claim_token`` is the attempt token the last ``_claim``
    returned — the batch executor presents it back so a stale dispatch
    (retried elsewhere meanwhile) cannot complete the handle.
    ``last_error`` records the most recent infrastructure failure so
    retry-budget exhaustion surfaces the ORIGINAL error, not a generic
    "gave up".

    Streaming round chunks (docs/SERVING.md "Streaming sessions")
    reuse this lifecycle unchanged: ``rounds`` is the chunk's round
    count (None for ordinary one-shot submissions — the dispatcher
    branches on it), ``meas_bits`` is then ``[rounds, n_shots,
    n_cores, n_meas]``, ``decode`` the optional static
    :class:`~...ops.decode.DecodeSpec`, and ``sid`` the owning
    session id.  Retry/steal/cancel semantics — including the attempt
    token — are inherited, which is exactly what makes stream chunks
    survive a chaos kill without lost or duplicated rounds.
    """
    mp: object
    meas_bits: object
    init_regs: object
    cfg: object
    strict: bool
    n_shots: int
    priority: int
    deadline: float
    seq: int
    handle: RequestHandle = field(default_factory=RequestHandle)
    submit_t: float = field(default_factory=time.monotonic)
    migrations: int = 0
    claim_token: int = 0
    last_error: BaseException = None
    rounds: int = None
    decode: object = None
    sid: int = None
    # tenant identity (docs/SERVING.md "Tenants"): every request
    # belongs to exactly one tenant; unattributed traffic lands on the
    # 'default' tenant at admission so the fair queue and the meters
    # never see None
    tenant: str = 'default'

    def expired(self, now: float) -> bool:
        """Whether the deadline has passed as of ``now`` (False when no
        deadline is armed) — shared by queue pruning and the stolen-
        batch re-queue check."""
        return self.deadline is not None and now >= self.deadline
