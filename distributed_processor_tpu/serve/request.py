"""Request lifecycle for the execution service: the future-like handle.

A submission is decoupled from its execution: :meth:`ExecutionService.
submit` returns a :class:`RequestHandle` immediately and the dispatcher
thread fulfils it after the request rides a coalesced batch through the
interpreter.  The handle is the only object a submitter touches, so its
state machine is deliberately small and fully lock-guarded:

``queued``      in the coalescer, cancellable, deadline armed
``dispatched``  claimed by the dispatcher for the batch being built —
                cancellation no longer possible (the batch boundary IS
                the cancellation point)
``done``        result or exception set, ``result()`` unblocked

The states only move forward, and every transition happens under the
handle's own lock, so ``cancel()`` racing the dispatcher's claim has
exactly one winner.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


class QueueFullError(RuntimeError):
    """Admission control: the service's bounded queue is full.

    Raised by ``submit`` (never stored on a handle) — backpressure is
    synchronous so the caller can shed load or retry, instead of the
    queue growing without bound under overload.
    """


class CancelledError(RuntimeError):
    """The request was cancelled (``handle.cancel()`` or a non-draining
    shutdown) before it was dispatched."""


class DeadlineError(RuntimeError):
    """The request's deadline passed before a batch picked it up.

    Deadlines are honored at BATCH BOUNDARIES: a request already
    claimed for a batch runs to completion (the interpreter cannot be
    interrupted mid-dispatch); one still queued when its deadline
    expires is failed with this error at the next dispatch opportunity.
    """


class ServiceClosedError(RuntimeError):
    """``submit`` after ``shutdown`` began."""


_QUEUED, _DISPATCHED, _DONE = 'queued', 'dispatched', 'done'


class RequestHandle:
    """Future-like handle for one submitted program.

    ``result(timeout)`` blocks for the per-request stats dict (the
    exact :func:`~...sim.interpreter.simulate_batch` schema, demuxed
    from the coalesced batch), re-raising the request's failure —
    :class:`~...sim.interpreter.FaultError` under strict fault mode,
    :class:`CancelledError`, :class:`DeadlineError` — and raising
    :class:`TimeoutError` if nothing arrived within ``timeout``
    seconds (the request itself stays live).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._state = _QUEUED
        self._result = None
        self._exception = None

    # -- submitter side -------------------------------------------------

    def result(self, timeout: float = None) -> dict:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f'request not completed within {timeout!r} s '
                f'(still {self._state})')
        if self._exception is not None:
            raise self._exception
        return self._result

    def exception(self, timeout: float = None):
        """The stored failure (or None), same blocking as ``result``."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f'request not completed within {timeout!r} s')
        return self._exception

    def done(self) -> bool:
        return self._event.is_set()

    def cancelled(self) -> bool:
        return isinstance(self._exception, CancelledError)

    def cancel(self) -> bool:
        """Cancel if still queued.  Returns True when this call won —
        the request will never execute and ``result()`` raises
        :class:`CancelledError`.  Returns False when the request was
        already claimed by a batch or already done: past the batch
        boundary there is nothing left to cancel."""
        return self._fail(CancelledError('request cancelled'),
                          only_queued=True)

    # -- service side ---------------------------------------------------

    def _claim(self) -> bool:
        """Dispatcher: move queued -> dispatched; False if the request
        was cancelled/failed first (the batch must skip it)."""
        with self._lock:
            if self._state != _QUEUED:
                return False
            self._state = _DISPATCHED
            return True

    def _fulfill(self, result: dict) -> None:
        with self._lock:
            if self._state == _DONE:        # pragma: no cover - defensive
                return
            self._state = _DONE
            self._result = result
        self._event.set()

    def _fail(self, exc: BaseException, only_queued: bool = False) -> bool:
        with self._lock:
            if self._state == _DONE or \
                    (only_queued and self._state != _QUEUED):
                return False
            self._state = _DONE
            self._exception = exc
        self._event.set()
        return True


@dataclass
class Request:
    """One normalized submission, as the batcher sees it.

    ``meas_bits`` / ``init_regs`` are already validated and in their
    full per-shot forms (``[n_shots, n_cores, n_meas]`` /
    ``[n_shots, n_cores, N_REGS]`` or None); ``cfg`` is the normalized
    count-mode :class:`InterpreterConfig` that is part of the bucket
    key; ``strict`` records whether THIS request (not its batch-mates)
    wants ``FaultError`` on trapped shots.  ``deadline`` is an absolute
    ``time.monotonic()`` value or None; ``seq`` is the service-wide
    arrival number used as the FIFO tiebreak inside a priority lane.
    ``migrations`` counts how many times work stealing moved this
    request between per-device queues (each hop re-runs the
    deadline/cancel checks at the re-queue boundary).
    """
    mp: object
    meas_bits: object
    init_regs: object
    cfg: object
    strict: bool
    n_shots: int
    priority: int
    deadline: float
    seq: int
    handle: RequestHandle = field(default_factory=RequestHandle)
    submit_t: float = field(default_factory=time.monotonic)
    migrations: int = 0

    def expired(self, now: float) -> bool:
        """Whether the deadline has passed as of ``now`` (False when no
        deadline is armed) — shared by queue pruning and the stolen-
        batch re-queue check."""
        return self.deadline is not None and now >= self.deadline
