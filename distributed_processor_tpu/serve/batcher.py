"""Shape-bucketed coalescing: which requests may share one dispatch.

The whole point of the service is that co-batched requests hit the
multi-program engine's WARM jit cache (``simulate_multi_batch`` keys on
the bucket SHAPE, not program content — PR 1's amortization).  So the
coalescing key is exactly the set of things that pick a compiled
executable or change its semantics:

* ``n_cores`` — the stacked tensor's core axis;
* ``isa.shape_bucket(n_instr)`` — the power-of-two instruction bucket
  every member is DONE-padded into;
* the element geometry tuple — stacked programs share one set of
  per-core sample-rate constants (``stack_machine_programs`` would
  reject a mismatch; keying on it means mismatched submissions simply
  land in different buckets instead of failing a batch);
* the normalized :class:`InterpreterConfig` — a static jit argument;
* the :func:`~..sim.interpreter.program_traits` tuple — also a static
  jit argument, so coalescing across trait sets would both fragment
  the warm cache (the stacked batch's trait-union picks a third
  executable neither member compiled) and make the dispatched
  executable depend on batch composition.

The key is a :class:`~.bucketspec.BucketSpec` (unbound template): the
same value the AOT warmup path compiles against and the learned bucket
catalog persists — one identity from admission to XLA.

Shot counts are deliberately NOT part of the key: short requests are
padded up to the batch's shot count by replicating their own rows
(deterministic execution makes replica lanes observationally inert;
``demux_multi_batch`` trims them back off).  Warmup *binds* the
template to concrete ``(n_programs, n_shots)`` before compiling.

Inside a bucket, requests order by priority lane (higher first) with
FIFO arrival as the tiebreak; a bucket becomes ripe when it holds
``max_batch_programs`` requests or its oldest member has waited
``max_wait_ms`` — the classic continuous-batching latency/throughput
dial (docs/SERVING.md).

With tenant fair queueing on (docs/SERVING.md "Tenants"), a deficit
round-robin layer sits ABOVE that order: each ``pop_batch`` replenishes
every backlogged tenant's credit by its configured weight, serves the
most-credited tenant first, and charges one credit per claimed request
— so claim order interleaves tenants by weight instead of strict
global FIFO, and a greedy tenant's thousandth request cannot starve a
victim's first.  Within a tenant, (priority desc, arrival asc) order
is unchanged, and a single-tenant queue reduces exactly to the legacy
behavior.
"""

from __future__ import annotations

import time

from .bucketspec import BucketSpec
from .request import DeadlineError, Request


def bucket_key(mp, cfg) -> BucketSpec:
    """The coalescing key: requests with equal keys may share a batch."""
    return BucketSpec.from_program(mp, cfg)


def shed_exempt(req: Request) -> bool:
    """Work the overload shedder may NEVER evict, regardless of another
    tenant's admission pressure: in-flight stream chunks (``rounds``/
    ``sid`` set — killing one round breaks a live session's exactly-
    once contract) and service-internal work carrying a negative
    ``seq`` (canary probes, SDC audit re-executions)."""
    return req.rounds is not None or req.sid is not None or req.seq < 0


class Coalescer:
    """Per-bucket pending queues.  NOT thread-safe on its own: every
    method is called under the service's lock — the coalescer is the
    data structure, the service owns the concurrency."""

    def __init__(self, max_batch_programs: int, max_wait_s: float,
                 tenant_weights: dict = None):
        self.max_batch_programs = max_batch_programs
        self.max_wait_s = max_wait_s
        # weighted fair queueing: None keeps the legacy global
        # (priority, arrival) claim order; a dict — the service's LIVE
        # view of configured weights, unknown tenants defaulting to
        # 1.0 — turns on deficit round-robin across tenants
        self._weights = tenant_weights
        self._deficit: dict = {}    # tenant -> accumulated DRR credit
        self._buckets: dict = {}     # key -> list[Request], arrival order
        self._depth = 0
        # buckets that ripened elsewhere and were migrated in by work
        # stealing: already past the latency dial once, so they stay
        # immediately dispatchable here even when the migration dropped
        # them below the count threshold
        self._forced: set = set()
        # requests observed leaving via handle.cancel() (dropped during
        # pruning or lost the claim race) — the service folds this into
        # its stats() 'cancelled' count
        self.dropped_cancelled = 0

    def __len__(self) -> int:
        return self._depth

    def push(self, key: tuple, req: Request,
             forced: bool = False) -> None:
        """``forced`` marks the bucket immediately dispatchable — used
        when re-queueing a parked retry, which already waited out its
        backoff and must not sit through the latency dial again."""
        self._buckets.setdefault(key, []).append(req)
        self._depth += 1
        if forced:
            self._forced.add(key)

    def cancel_all(self, exc: BaseException) -> int:
        """Fail every queued request (non-draining shutdown)."""
        n = 0
        for reqs in self._buckets.values():
            for req in reqs:
                if req.handle._fail(exc):
                    n += 1
        self._buckets.clear()
        self._forced.clear()
        self._depth = 0
        return n

    def _prune(self, now: float) -> list:
        """Drop cancelled requests; fail expired ones (batch-boundary
        deadline semantics).  Returns the expired requests so the
        service can count them."""
        expired = []
        for key in list(self._buckets):
            kept = []
            for req in self._buckets[key]:
                if req.handle.done():           # cancelled meanwhile
                    self._depth -= 1
                    if req.handle.cancelled():
                        self.dropped_cancelled += 1
                elif req.expired(now):
                    self._depth -= 1
                    if req.handle._fail(DeadlineError(
                            f'deadline passed while queued '
                            f'({now - req.submit_t:.3f} s after '
                            f'submission)')):
                        expired.append(req)
                else:
                    kept.append(req)
            if kept:
                self._buckets[key] = kept
            else:
                del self._buckets[key]
                self._forced.discard(key)
        return expired

    def _ripe(self, reqs: list, now: float, flush: bool) -> bool:
        if flush or len(reqs) >= self.max_batch_programs:
            return True
        return (now - min(r.submit_t for r in reqs)) >= self.max_wait_s

    def pop_batch(self, now: float = None, flush: bool = False):
        """Claim and return the next batch:
        ``(key, [Request, ...], expired)`` — ``key`` is None when
        nothing is ripe (``expired`` lists deadline-failed requests
        either way).

        Among ripe buckets the one whose best request has the highest
        priority wins (oldest arrival breaks the tie); within the
        bucket, up to ``max_batch_programs`` requests leave in
        (priority desc, arrival asc) order.  With tenant fair queueing
        on, deficit round-robin picks the serving tenant first and the
        bucket/claim order interleaves tenants by weight (see module
        docstring).  Every returned request has been atomically
        claimed — ``cancel()`` on it returns False from here on.
        """
        if now is None:
            now = time.monotonic()
        expired = self._prune(now)
        ripe = {key: reqs for key, reqs in self._buckets.items()
                if key in self._forced or self._ripe(reqs, now, flush)}
        if not ripe:
            return None, [], expired
        if self._weights is None:
            best_key, best_rank = None, None
            for key, reqs in ripe.items():
                head = min(reqs, key=lambda r: (-r.priority, r.seq))
                rank = (-head.priority, head.seq)
                if best_rank is None or rank < best_rank:
                    best_key, best_rank = key, rank
            reqs = sorted(self._buckets[best_key],
                          key=lambda r: (-r.priority, r.seq))
            take, leave = (reqs[:self.max_batch_programs],
                           reqs[self.max_batch_programs:])
        else:
            best_key, take, leave = self._pop_drr(ripe)
        batch = []
        for r in take:
            tok = r.handle._claim()
            if tok:
                # the attempt token travels with the request: the
                # executor presents it back at fulfill/fail time, so a
                # dispatch that hung (and whose request was retried
                # elsewhere) can never double-complete the handle
                r.claim_token = tok
                batch.append(r)
            elif r.handle.cancelled():   # lost the race to cancel()
                self.dropped_cancelled += 1
        if leave:
            self._buckets[best_key] = sorted(leave, key=lambda r: r.seq)
        else:
            del self._buckets[best_key]
            self._forced.discard(best_key)
        self._depth -= len(take)
        if not batch:       # every candidate was cancelled in the race
            return None, [], expired
        return best_key, batch, expired

    def _weight(self, tenant: str) -> float:
        try:
            w = float(self._weights.get(tenant, 1.0))
        except (TypeError, ValueError):
            w = 1.0
        return w if w > 0 else 1.0

    def _pop_drr(self, ripe: dict):
        """Deficit-round-robin selection: pick the serving tenant, then
        the bucket holding its best work, then claim up to
        ``max_batch_programs`` requests interleaving tenants.  Returns
        ``(key, take, leave)`` for ``pop_batch`` to claim/write back.

        Classic DRR rules: every tenant with ripe backlog earns its
        weight in credit per visit (capped at weight x batch size so an
        idle-then-bursting tenant cannot bank unbounded credit), a
        drained tenant forfeits its credit, and each claimed request
        costs one credit.  A single-tenant queue degenerates to the
        legacy (priority desc, arrival asc) order exactly.
        """
        oldest = {}
        for reqs in ripe.values():
            for r in reqs:
                if r.tenant not in oldest or r.seq < oldest[r.tenant]:
                    oldest[r.tenant] = r.seq
        for t in list(self._deficit):
            if t not in oldest:
                del self._deficit[t]
        cap = float(max(self.max_batch_programs, 1))
        for t in oldest:
            w = self._weight(t)
            # cap floor of 1.0 x batch: a sub-unit weight must still
            # be able to bank one whole credit, or it could never claim
            self._deficit[t] = min(self._deficit.get(t, 0.0) + w,
                                   max(w, 1.0) * cap)
        serve = min(oldest,
                    key=lambda t: (-self._deficit[t], oldest[t]))
        best_key, best_rank = None, None
        for key, reqs in ripe.items():
            mine = [r for r in reqs if r.tenant == serve]
            if not mine:
                continue
            head = min(mine, key=lambda r: (-r.priority, r.seq))
            rank = (-head.priority, head.seq)
            if best_rank is None or rank < best_rank:
                best_key, best_rank = key, rank
        by_t = {}
        for r in self._buckets[best_key]:
            by_t.setdefault(r.tenant, []).append(r)
        for q in by_t.values():
            q.sort(key=lambda r: (-r.priority, r.seq))
        torder = sorted(by_t, key=lambda t: (
            t != serve, -self._deficit.get(t, 0.0),
            min(r.seq for r in by_t[t])))
        take = []
        while len(take) < self.max_batch_programs \
                and any(by_t.values()):
            progressed = False
            for t in torder:
                q = by_t[t]
                while q and len(take) < self.max_batch_programs \
                        and self._deficit.get(t, 0.0) >= 1.0:
                    take.append(q.pop(0))
                    self._deficit[t] -= 1.0
                    progressed = True
            if not progressed:
                # credit exhausted with batch slots still open: start
                # another DRR round for the tenants still backlogged
                # HERE, so one pop's composition honors the weights
                # (weight w > 0 guarantees this replenish eventually
                # banks a whole credit — the loop terminates)
                for t in torder:
                    if by_t[t]:
                        w = self._weight(t)
                        self._deficit[t] = min(
                            self._deficit.get(t, 0.0) + w,
                            max(w, 1.0) * cap)
        leave = [r for q in by_t.values() for r in q]
        return best_key, take, leave

    def ripe_keys(self, now: float = None, flush: bool = False) -> list:
        """Keys of the buckets a dispatcher could claim right now, best
        head first (the order ``pop_batch`` would prefer them).  A pure
        view — no pruning, no claiming — used by the work-stealing path
        to pick a victim bucket; staleness is fine because ``absorb``
        re-validates every request at the re-queue boundary."""
        if now is None:
            now = time.monotonic()
        ranked = []
        for key, reqs in self._buckets.items():
            live = [r for r in reqs if not r.handle.done()]
            if not live:
                continue
            if key not in self._forced \
                    and not self._ripe(live, now, flush):
                continue
            head = min(live, key=lambda r: (-r.priority, r.seq))
            ranked.append(((-head.priority, head.seq), key))
        return [key for _, key in sorted(ranked)]

    def migrate_bucket(self, key: tuple, max_n: int) -> list:
        """Remove up to ``max_n`` requests from ``key``'s bucket in
        claim order (priority desc, arrival asc) WITHOUT claiming them
        — work stealing moves whole ripened batches between per-device
        queues, and the requests must stay cancellable in flight.  The
        receiving queue's :meth:`absorb` re-runs the deadline/cancel
        checks the requests aged past while queued here."""
        reqs = self._buckets.get(key)
        if not reqs:
            return []
        ranked = sorted(reqs, key=lambda r: (-r.priority, r.seq))
        take, leave = ranked[:max_n], ranked[max_n:]
        if leave:
            self._buckets[key] = sorted(leave, key=lambda r: r.seq)
        else:
            del self._buckets[key]
            self._forced.discard(key)
        self._depth -= len(take)
        return take

    def migrate_all(self) -> dict:
        """Remove EVERY queued request, keyed by bucket — the
        quarantine path: a tripped/lost executor's whole backlog
        re-homes onto healthy executors via their :meth:`absorb` (which
        re-runs the deadline/cancel checks, exactly like a work-steal
        migration)."""
        out = {key: sorted(reqs, key=lambda r: (-r.priority, r.seq))
               for key, reqs in self._buckets.items()}
        self._buckets.clear()
        self._forced.clear()
        self._depth = 0
        return out

    def shed_candidate(self, below_priority: int,
                       tenant_pressure: dict = None):
        """The single most-sheddable queued request strictly below
        ``below_priority`` — the most-over-quota tenant first (per the
        service-supplied ``tenant_pressure`` map, higher = more over
        quota), then lowest priority, then newest arrival within it
        (the request that has invested the least waiting) — as
        ``(key, req)``, or None.  Stream chunks and service-internal
        work are exempt (:func:`shed_exempt`): another tenant's
        admission pressure must never break a live session.  A pure
        view: the service compares candidates ACROSS executor queues
        before calling :meth:`remove` on the loser's, then fails it
        with ``OverloadError`` (the overload-control eviction path)."""
        worst, worst_key, worst_rank = None, None, None
        for key, reqs in self._buckets.items():
            for r in reqs:
                if r.priority >= below_priority or r.handle.done():
                    continue
                if shed_exempt(r):
                    continue
                p = 0.0 if tenant_pressure is None else \
                    float(tenant_pressure.get(r.tenant, 0.0))
                rank = (-p, r.priority, -r.seq)
                if worst_rank is None or rank < worst_rank:
                    worst, worst_key, worst_rank = r, key, rank
        if worst is None:
            return None
        return worst_key, worst

    def remove(self, key: tuple, req: Request) -> bool:
        """Drop one specific queued request (the shed eviction);
        False when it already left the queue some other way."""
        reqs = self._buckets.get(key)
        if not reqs or req not in reqs:
            return False
        reqs.remove(req)
        if not reqs:
            del self._buckets[key]
            self._forced.discard(key)
        self._depth -= 1
        return True

    def absorb(self, key: tuple, reqs: list, now: float = None) -> list:
        """Re-queue migrated requests: the stolen batch's landing point.

        A request cancelled in flight is dropped (counted in
        ``dropped_cancelled``); one whose deadline passed while it sat
        in the victim's queue is failed with :class:`DeadlineError`
        HERE, at the re-queue boundary, so a migrated request can never
        outlive its ``deadline_ms`` silently.  Returns the expired
        requests for the service's stats."""
        if now is None:
            now = time.monotonic()
        expired = []
        for req in reqs:
            if req.handle.done():
                if req.handle.cancelled():
                    self.dropped_cancelled += 1
                continue
            if req.expired(now):
                if req.handle._fail(DeadlineError(
                        f'deadline passed while queued (expired during '
                        f'work-steal migration, {now - req.submit_t:.3f}'
                        f' s after submission)')):
                    expired.append(req)
                continue
            req.migrations += 1
            req.handle.migrations = req.migrations
            if req.handle._trace is not None:
                # the migration hop in the request's span chain
                # (steal / quarantine re-home — docs/OBSERVABILITY.md)
                req.handle._trace.instant('migrate', t=now,
                                          hop=req.migrations)
            self.push(key, req)
            # the batch already ripened at the victim; keep it
            # immediately dispatchable here even if the migration
            # dropped it below the count threshold
            self._forced.add(key)
        return expired

    def next_event(self, now: float = None) -> float:
        """Seconds until the next scheduled wake-up (a bucket ripening
        or a deadline expiring), or None when the queue is empty — the
        dispatcher's condition-wait timeout."""
        if not self._buckets:
            return None
        if self._forced:
            return 0.0          # a migrated-in bucket is ready now
        if now is None:
            now = time.monotonic()
        horizon = None
        for reqs in self._buckets.values():
            oldest = min(r.submit_t for r in reqs)
            events = [oldest + self.max_wait_s]
            events += [r.deadline for r in reqs if r.deadline is not None]
            t = min(events)
            horizon = t if horizon is None else min(horizon, t)
        return max(horizon - now, 0.0)
