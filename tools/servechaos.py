#!/usr/bin/env python
"""Serve-layer chaos soak driver: no injected fault may HANG or CORRUPT.

Stands up a live ExecutionService, wraps its ``_run_batch`` with the
seeded :class:`~distributed_processor_tpu.serve.chaos.ChaosMonkey`
(crashes, hangs past the watchdog, slowdowns, dispatcher deaths), and
soaks it with a stream of requests.  The pass criteria are the serving
contract under fire (docs/ROBUSTNESS.md "serving-layer failures"):

* every handle terminates — zero ``result()`` timeouts;
* every completion is bit-identical to its solo ``simulate_batch`` run;
* every failure is a TYPED error (retry budget exhausted surfaces the
  original fault, shutdown surfaces ShutdownError, ...).

Deterministic in ``--seed`` (injection draws are serialized under one
lock; thread interleaving varies but the outcome invariants must hold
for every interleaving — that is the point).  Exit nonzero on any
violation.  The sim-layer analogue is tools/faultfuzz.py; this is the
same discipline one tier up:

    python tools/servechaos.py --quick         # ~30 s, 60 requests
    python tools/servechaos.py                 # full: 200 requests

``--fleet N`` moves the soak one tier up (docs/FLEET.md): N replica
PROCESSES behind a FleetRouter, with scripted process-level chaos —
SIGKILL one replica mid-stream (the monitor respawns it from the
shared warm tiers), SIGSTOP-wedge another past the gossip liveness
window, SIGCONT it back into re-admission.  Same contract, plus:
goodput must stay positive inside the kill window.

    python tools/servechaos.py --fleet 2 --quick

``--corrupt`` turns the soak into a silent-data-corruption drill
(docs/ROBUSTNESS.md "Integrity"): single mode flips one bit in
completed result stats (``ChaosPlan.p_corrupt``) under a strict
``audit_sample=1`` differential auditor; fleet mode flips one bit in
received wire frames against the frame CRCs and end-to-end digests.
Pass bar either way: every flip detected-and-typed or
retried-to-correct — zero silently-wrong bits, zero hangs.

    python tools/servechaos.py --corrupt --quick
    python tools/servechaos.py --corrupt --fleet 2 --quick

``--tenants N`` tags every submission to one of N tenants and audits
the billing meters against caller-side ground truth after the soak
(docs/SERVING.md "Tenants"); ``--greedy`` makes tenant ``t0`` flood
admission (extra slots per cycle, weight 1, a queued-requests cap)
while the others trickle at weight 4.  Pass bar on top of the serving
contract: the service's ``tenant.*`` meters must match what this
driver observed EXACTLY — chaos retries may neither lose nor
double-bill a tenant's usage — and with ``--greedy`` no victim
request may be shed.

    python tools/servechaos.py --tenants 3 --greedy --quick
"""

import argparse
import json
import os
import sys
import time

# the multi-device soak needs >= 2 devices; force a virtual 2-device
# CPU before jax initialises (a no-op when a real multi-device platform
# or the test conftest already configured one)
if 'JAX_PLATFORMS' not in os.environ:
    os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=2').strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('--quick', action='store_true',
                    help='CI mode: 60 requests, milder injection')
    ap.add_argument('-n', type=int, default=None,
                    help='request count (default 60 quick / 200 full)')
    ap.add_argument('--seed', type=int, default=0,
                    help='soak seed (bits + injection draws)')
    ap.add_argument('--devices', type=int, default=2,
                    help='executor pool size (default 2)')
    ap.add_argument('--shots', type=int, default=4)
    ap.add_argument('--qubits', type=int, default=2)
    ap.add_argument('--depth', type=int, default=2)
    ap.add_argument('--p-crash', type=float, default=0.10)
    ap.add_argument('--p-hang', type=float, default=0.03)
    ap.add_argument('--p-slow', type=float, default=0.10)
    ap.add_argument('--p-die', type=float, default=0.02)
    ap.add_argument('--corrupt', action='store_true',
                    help='silent-data-corruption soak: inject bit '
                         'flips into completed result stats (single '
                         'mode, via ChaosMonkey p_corrupt + a strict '
                         'audit_sample=1 auditor) or into received '
                         'wire frames (--fleet mode, via the '
                         'transport corruptor hook + frame CRCs); '
                         'every flip must be detected-and-typed or '
                         'retried-to-correct — zero silently-wrong '
                         'bits (docs/ROBUSTNESS.md "Integrity")')
    ap.add_argument('--p-corrupt', type=float, default=0.25,
                    help='per-batch result corruption probability '
                         'under --corrupt (single mode)')
    ap.add_argument('--min-corrupt', type=int, default=None,
                    help='fail unless at least this many corruptions '
                         'were injected (default: scaled to -n)')
    ap.add_argument('--hang-s', type=float, default=1.0,
                    help='injected hang duration (past the watchdog)')
    ap.add_argument('--json', action='store_true',
                    help='emit the report as JSON on stdout')
    ap.add_argument('--flight-out', default=None, metavar='PATH',
                    help='dump the full flight-recorder ring to PATH '
                         '(the exit report always carries counts + the '
                         'event tail); in --fleet mode this is the '
                         'FEDERATED ring — router + every replica, '
                         'live-pulled or last-gossiped, time-aligned '
                         'onto the router clock')
    ap.add_argument('--trace-out', default=None, metavar='PATH',
                    help='trace every request (sample=1.0) and export '
                         'the Chrome-trace JSON to PATH; in --fleet '
                         'mode the trace is the STITCHED cross-process '
                         'waterfall (router + clock-aligned replica '
                         'spans on one tid per request)')
    ap.add_argument('--fleet', type=int, default=0, metavar='N',
                    help='soak a fleet of N replica processes '
                         '(SIGKILL/SIGSTOP chaos) instead of the '
                         'in-process service')
    ap.add_argument('--tenants', type=int, default=0, metavar='N',
                    help='multi-tenant soak: tag every submission to '
                         'one of N tenants and audit the billing '
                         'meters against caller-side ground truth '
                         '(exactly-once under chaos retries; '
                         'docs/SERVING.md "Tenants")')
    ap.add_argument('--greedy', action='store_true',
                    help='with --tenants: tenant t0 floods admission '
                         '(extra submission slots, weight 1, queued '
                         'cap) while the others trickle at weight 4; '
                         'adds the isolation pass bar — zero victim '
                         'sheds, greedy overflow typed against its '
                         'own quota')
    ap.add_argument('--rate-hz', type=float, default=30.0,
                    help='fleet-mode submission pacing (default 30)')
    args = ap.parse_args(argv)

    if args.greedy and not args.tenants:
        ap.error('--greedy needs --tenants N')
    if args.fleet:
        if args.tenants:
            ap.error('--tenants runs against the in-process service; '
                     'drop --fleet')
        return _fleet_mode(args)

    from distributed_processor_tpu.serve import (ChaosMonkey, ChaosPlan,
                                                 ExecutionService,
                                                 RetryPolicy)
    from distributed_processor_tpu.serve.benchmark import _workload
    from distributed_processor_tpu.serve.chaos import soak, tenant_soak

    n = args.n if args.n is not None else (60 if args.quick else 200)
    p_crash = args.p_crash * (0.5 if args.quick else 1.0)
    p_die = args.p_die * (0.5 if args.quick else 1.0)
    p_corrupt = args.p_corrupt if args.corrupt else 0.0
    mps, _bits, cfg = _workload(min(n, 12), args.qubits, args.depth,
                                args.shots, args.seed)
    plan = ChaosPlan(seed=args.seed, p_crash=p_crash, p_hang=args.p_hang,
                     p_slow=args.p_slow, p_die=p_die,
                     p_corrupt=p_corrupt,
                     hang_s=args.hang_s, slow_s=0.01)
    # under --corrupt the auditor IS the detector: audit every batch,
    # strict mode so tainted bits are failed-and-retried, never served
    integrity_kwargs = dict(audit_sample=1.0, audit_mode='strict') \
        if args.corrupt else {}
    names, greedy, tenant_kwargs = None, None, {}
    if args.tenants:
        names = [f't{i}' for i in range(max(2, args.tenants))]
        greedy = names[0] if args.greedy else None
        tcfg = {t: {'weight': 4.0} for t in names}
        if greedy is not None:
            tcfg[greedy] = {'weight': 1.0, 'max_queued': max(8, n // 8)}
        tenant_kwargs = {'tenants': tcfg}
    t0 = time.monotonic()
    with ExecutionService(
            cfg, max_batch_programs=4, max_wait_ms=5.0,
            max_queue=4 * n, devices=args.devices,
            retry_policy=RetryPolicy(max_attempts=6, backoff_s=0.01),
            hang_timeout_s=0.4, breaker_threshold=3,
            breaker_cooldown_ms=100.0,
            supervise_interval_ms=10.0,
            trace_sample=1.0 if args.trace_out else 0.0,
            trace_keep=4 * n, **integrity_kwargs,
            **tenant_kwargs) as svc:
        with ChaosMonkey(svc, plan) as monkey:
            if names is not None:
                report = tenant_soak(svc, mps, cfg, tenants=names,
                                     n_requests=n, shots=args.shots,
                                     seed=args.seed, greedy=greedy,
                                     result_timeout_s=120.0)
            else:
                report = soak(svc, mps, cfg, n_requests=n,
                              shots=args.shots, seed=args.seed,
                              result_timeout_s=120.0)
        stats = svc.stats()
        flight = svc.flight_recorder
        if args.flight_out:
            flight.dump(args.flight_out)
        if args.trace_out:
            svc.dump_trace(args.trace_out)
    wall_s = time.monotonic() - t0

    out = {
        'requests': n,
        'devices': args.devices,
        'seed': args.seed,
        'injected': dict(monkey.injected),
        'submitted': report.submitted,
        'rejected': report.rejected,
        'completed': report.completed,
        'hung': report.hung,
        'bit_mismatches': report.bit_mismatches,
        'failed_typed': dict(report.errors),
        'retries': stats['retries'],
        'retry_exhausted': stats['retry_exhausted'],
        'breaker_trips': stats['breaker_trips'],
        'readmissions': stats['readmissions'],
        'hangs_detected': stats['hangs'],
        'executor_deaths': stats['executor_deaths'],
        'integrity': stats['integrity'],
        'wall_s': round(wall_s, 3),
        # the incident timeline: what the chaos actually did, in order
        # (docs/OBSERVABILITY.md "flight recorder")
        'flight_recorder': {
            'recorded': flight.recorded,
            'counts': flight.counts(),
            'tail': flight.events()[-20:],
        },
    }
    if names is not None:
        out['tenants'] = report.per_tenant
        out['meter_mismatches'] = report.meter_mismatches
    failures = []
    if report.hung:
        failures.append(f'{report.hung} handle(s) HUNG past the '
                        f'result timeout')
    if report.bit_mismatches:
        failures.append(f'{report.bit_mismatches} completion(s) not '
                        f'bit-identical to the solo run')
    # every ACCEPTED handle must terminate: typed submit refusals are
    # counted in errors too, so net them out of the terminated total
    if report.terminated() - report.rejected != report.submitted:
        missing = report.submitted + report.rejected \
            - report.terminated()
        failures.append(f'{missing} handle(s) neither completed nor '
                        f'typed-failed')
    if names is not None:
        for msg in report.meter_mismatches:
            failures.append(f'billing meter mismatch: {msg}')
        if greedy is not None:
            for t in names:
                if t != greedy and report.per_tenant[t]['shed']:
                    failures.append(
                        f'victim tenant {t} had '
                        f'{report.per_tenant[t]["shed"]} request(s) '
                        f'shed under greedy pressure')
    if args.corrupt:
        n_corrupt = int(out['injected'].get('corrupt', 0))
        min_corrupt = args.min_corrupt if args.min_corrupt is not None \
            else (8 if args.quick else 25)
        if n_corrupt < min_corrupt:
            failures.append(f'only {n_corrupt} corruption(s) injected '
                            f'(need >= {min_corrupt}): the soak did '
                            f'not exercise the auditor')
        if n_corrupt and not stats['integrity']['mismatches']:
            failures.append(f'{n_corrupt} corruption(s) injected but '
                            f'the auditor flagged ZERO mismatches')
    out['ok'] = not failures
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        for k, v in out.items():
            print(f'{k:>18}: {v}')
    for msg in failures:
        print(f'SERVECHAOS FAIL: {msg}', file=sys.stderr)
    return 1 if failures else 0


def _fleet_mode(args) -> int:
    """Fleet soak: N replica processes, scripted process-level chaos."""
    from distributed_processor_tpu.serve.benchmark import _workload
    from distributed_processor_tpu.serve.chaos import fleet_soak
    from distributed_processor_tpu.serve.fleet import Fleet
    from distributed_processor_tpu.serve.supervise import RetryPolicy

    n = args.n if args.n is not None else (60 if args.quick else 150)
    n_rep = max(2, args.fleet)
    mps, _bits, cfg = _workload(min(n, 12), args.qubits, args.depth,
                                args.shots, args.seed)
    # SIGKILL the loaded replica (-1 resolves at fire time) a third of
    # the way in — the monitor respawns it from the shared warm tiers;
    # wedge + unwedge the then-loaded one so the gossip-staleness and
    # re-admission paths both fire
    actions = [(n // 3, 'kill', -1),
               (n // 2, 'wedge', -1), ((3 * n) // 4, 'unwedge', -1)]
    t0 = time.monotonic()
    with Fleet(
            n_rep,
            interp_cfg=None,
            # --corrupt: program digests ride submits, result-stat
            # digests ride responses (docs/ROBUSTNESS.md "Integrity")
            integrity=args.corrupt,
            service={'max_batch_programs': 4, 'max_wait_ms': 5.0,
                     'max_queue': 4 * n,
                     'max_est_wait_ms': 10000.0},
            env={'XLA_FLAGS': '--xla_force_host_platform_device_count=1'},
            # stitched cross-process traces when requested: the router
            # samples, the decision rides the wire, replica spans come
            # back piggybacked (docs/OBSERVABILITY.md)
            trace_sample=1.0 if args.trace_out else 0.0,
            # the scripted kill+wedge can overlap into a total outage
            # until the respawn boots; a deep, slow budget parks the
            # recovered requests across it instead of exhausting
            router_kwargs={'retry_policy': RetryPolicy(
                max_attempts=10, backoff_s=0.05, backoff_mult=2.0,
                max_backoff_s=1.0),
                'trace_keep': 4 * n},
    ) as fleet:
        # warm EVERY replica on the workload bucket directly: bucket
        # affinity would home all of fleet.submit's warmup on one
        # replica, leaving the failover survivor to first-compile
        # under the post-kill herd
        for rid in fleet.replica_ids():
            fleet.router.call_replica(
                rid, 'submit',
                dict(mp=mps[0], meas_bits=_bits[0], cfg=cfg),
                timeout_s=600.0)
        # --corrupt: flip one bit in ~every 30th frame THIS process
        # receives (result frames and gossip pulls alike), after the
        # replica stamped its CRC — so what is under test is detection
        # and recovery (frame reset, gossip-cadence re-dial, cross-
        # replica retry), not the injection itself.  Installed after
        # warmup and removed before the post-mortem pulls.
        wire_injected = [0]
        prev_hook = None
        if args.corrupt:
            from distributed_processor_tpu.integrity import \
                flip_payload_bit
            from distributed_processor_tpu.serve import transport
            seen = [0]

            def _corruptor(data):
                seen[0] += 1
                if seen[0] % 30 == 0 and data:
                    wire_injected[0] += 1
                    return flip_payload_bit(
                        data, bit_index=(7 * seen[0]) % (len(data) * 8))
                return data

            prev_hook = transport.install_wire_corruptor(_corruptor)
        try:
            report = fleet_soak(fleet, mps, cfg, n_requests=n,
                                shots=args.shots, seed=args.seed,
                                rate_hz=args.rate_hz, actions=actions,
                                result_timeout_s=180.0)
        finally:
            if args.corrupt:
                from distributed_processor_tpu.serve import transport
                transport.install_wire_corruptor(prev_hook)
        stats = fleet.stats()
        # federated post-mortem: the router's ring + every replica's
        # (live-pulled where reachable, last gossiped digest where
        # not), time-aligned onto the router's clock
        merged = fleet.merged_flight(pull=True)
        if args.flight_out:
            tmp = f'{args.flight_out}.tmp.{os.getpid()}'
            with open(tmp, 'w') as f:
                json.dump(merged, f, indent=1)
            os.replace(tmp, args.flight_out)
        trace_events = fleet.dump_trace(args.trace_out) \
            if args.trace_out else 0
    wall_s = time.monotonic() - t0

    kill_t = next(t for t, m, _ in report.actions if m == 'kill')
    ok_in_kill = report.ok_in_window(kill_t, kill_t + 2.0)
    out = {
        'mode': 'fleet',
        'replicas': n_rep,
        'requests': n,
        'seed': args.seed,
        'actions': report.actions,
        'submitted': report.submitted,
        'rejected': report.rejected,
        'completed': report.completed,
        'hung': report.hung,
        'bit_mismatches': report.bit_mismatches,
        'failed_typed': dict(report.errors),
        'ok_in_kill_window': ok_in_kill,
        'goodput_rps': round(report.goodput(), 2),
        'router': {k: stats[k] for k in (
            'retries', 'retry_exhausted', 'failovers', 'replica_down',
            'replica_up', 'gossip_stale', 'breaker_trips',
            'readmissions', 'n_routable')},
        'respawns': {r: p['respawns']
                     for r, p in stats['processes'].items()},
        'slo_breaches': stats.get('slo_breaches', 0),
        'wire_corruptions_injected': wire_injected[0],
        'wall_s': round(wall_s, 3),
        'trace_events': trace_events,
        # federated incident timeline summary (--flight-out carries
        # the full time-aligned event stream)
        'flight': {
            'router': merged['router'],
            'replicas': merged['replicas'],
            'clock_offsets': merged['clock_offsets'],
            'events_merged': len(merged['events']),
            'tail': merged['events'][-12:],
        },
    }
    failures = []
    if report.hung:
        failures.append(f'{report.hung} handle(s) HUNG past the '
                        f'result timeout')
    if report.bit_mismatches:
        failures.append(f'{report.bit_mismatches} completion(s) not '
                        f'bit-identical to the solo run')
    if report.terminated() != report.submitted:
        failures.append(f'{report.submitted - report.terminated()} '
                        f'handle(s) neither completed nor typed-failed')
    if ok_in_kill == 0:
        failures.append('goodput hit ZERO inside the kill window')
    if args.corrupt:
        min_corrupt = args.min_corrupt if args.min_corrupt is not None \
            else (4 if args.quick else 10)
        if wire_injected[0] < min_corrupt:
            failures.append(f'only {wire_injected[0]} wire '
                            f'corruption(s) injected (need >= '
                            f'{min_corrupt}): the soak did not '
                            f'exercise the frame CRCs')
    out['ok'] = not failures
    if args.json:
        print(json.dumps(out, indent=2))
    else:
        for k, v in out.items():
            print(f'{k:>18}: {v}')
    for msg in failures:
        print(f'SERVECHAOS FAIL: {msg}', file=sys.stderr)
    return 1 if failures else 0


if __name__ == '__main__':
    sys.exit(main())
