#!/usr/bin/env python
"""Fail CI on any JUnit <failure>/<error> element.

The reference gates its pipelines the same way (reference:
cocotb/.check_xml.py, invoked from .gitlab-ci.yml) — a belt-and-braces
check that a runner swallowing pytest's exit code can't turn a red
suite green.
"""

import sys
import xml.etree.ElementTree as ET

# the stderr marker cli._fault_table / the sweep drivers print when any
# shot trapped a runtime fault: a GREEN testcase whose captured output
# carries it means a test exercised faulting execution without
# asserting on it — only fault-injection tests (named/marked 'fault')
# may trip the trap machinery
FAULT_MARK = 'fault summary (trapped shots'


def _is_fault_test(tc) -> bool:
    ident = f'{tc.get("classname", "")}.{tc.get("name", "")}'.lower()
    return 'fault' in ident


def main(path: str) -> int:
    root = ET.parse(path).getroot()
    failures = root.findall('.//failure') + root.findall('.//error')
    if failures:
        for f in failures:
            print(f'FAILURE: {f.get("message", "")[:200]}')
        return 1
    n_tests = sum(int(s.get('tests', 0))
                  for s in root.iter('testsuite')) or int(
                      root.get('tests', 0))
    if n_tests == 0:
        print('FAILURE: no tests ran')
        return 1
    leaks = []
    for tc in root.iter('testcase'):
        if _is_fault_test(tc):
            continue
        for out in (tc.findall('system-out') + tc.findall('system-err')):
            if out.text and FAULT_MARK in out.text:
                leaks.append(f'{tc.get("classname")}.{tc.get("name")}')
                break
    if leaks:
        for name in leaks:
            print(f'FAULT LEAK: {name}: nonzero fault_shots from a '
                  f'non-fault-injection test (see docs/ROBUSTNESS.md)')
        return 1
    print(f'junit OK: {n_tests} tests, no failures, no fault leaks')
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1]))
