#!/usr/bin/env python
"""Fail CI on any JUnit <failure>/<error> element.

The reference gates its pipelines the same way (reference:
cocotb/.check_xml.py, invoked from .gitlab-ci.yml) — a belt-and-braces
check that a runner swallowing pytest's exit code can't turn a red
suite green.
"""

import sys
import xml.etree.ElementTree as ET


def main(path: str) -> int:
    root = ET.parse(path).getroot()
    failures = root.findall('.//failure') + root.findall('.//error')
    if failures:
        for f in failures:
            print(f'FAILURE: {f.get("message", "")[:200]}')
        return 1
    n_tests = sum(int(s.get('tests', 0))
                  for s in root.iter('testsuite')) or int(
                      root.get('tests', 0))
    if n_tests == 0:
        print('FAILURE: no tests ran')
        return 1
    print(f'junit OK: {n_tests} tests, no failures')
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1]))
