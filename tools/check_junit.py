#!/usr/bin/env python
"""Fail CI on any JUnit <failure>/<error> element.

The reference gates its pipelines the same way (reference:
cocotb/.check_xml.py, invoked from .gitlab-ci.yml) — a belt-and-braces
check that a runner swallowing pytest's exit code can't turn a red
suite green.
"""

import sys
import xml.etree.ElementTree as ET

# the stderr marker cli._fault_table / the sweep drivers print when any
# shot trapped a runtime fault: a GREEN testcase whose captured output
# carries it means a test exercised faulting execution without
# asserting on it — only fault-injection tests (named/marked 'fault')
# may trip the trap machinery
FAULT_MARK = 'fault summary (trapped shots'

# the marker tests/conftest.py's autouse probe prints when an execution
# service dispatcher thread (serve/) survives a test: a green testcase
# carrying it left a live thread behind — services must be shut down
# (no exemptions: even serve tests may not leak their dispatchers)
LEAK_MARK = 'SERVICE THREAD LEAK'

# test modules whose cases may NEVER skip: the pallas exec-kernel suite
# runs under the kernel interpreter on CPU by design, so a skip there
# means the CPU ladder rung silently stopped being exercised (the
# test_tpu_kernels.py hardware gate is the one legitimate skip site and
# is not listed here)
# module -> why a skip there is a CI failure, printed verbatim
NO_SKIP_MODULES = {
    'test_exec_pallas':
        'pallas exec-kernel tests must run on CPU via interpret '
        'mode, never skip (see docs/PERF.md "megastep")',
    'test_exec_fused':
        'fused measure-in-megastep + packed-carry tests must run on '
        'CPU via interpret mode, never skip (see docs/PERF.md "fused '
        'epoch")',
    'test_compilecache':
        'compile front-door tests are pure CPU (numpy compile + '
        'content hashing), there is no legitimate skip condition — a '
        'skip means the cache/singleflight/invalidation contract '
        'stopped being exercised (see docs/COMPILE_CACHE.md)',
    'test_aot_warmup':
        'AOT warmup tests run on the forced CPU mesh (BucketSpec '
        'round-trips, aot_compile_batch bit-identity, catalog replay) '
        'with no hardware dependency — a skip means the cold-start '
        'contract (docs/SERVING.md "Cold start & warmup") stopped '
        'being exercised',
    'test_fleet':
        'fleet federation tests spawn replica subprocesses on plain '
        'localhost TCP + the forced CPU backend, with no hardware '
        'dependency — a skip means the replica-loss contract '
        '(docs/FLEET.md: failover bit-identity, gossip staleness, '
        'warm respawn) stopped being exercised',
    'test_integrity':
        'integrity-fabric tests (digests, wire checksums, audit '
        'sampler, scrubber quarantine) run on pure CPU + localhost '
        'sockets with no hardware dependency — a skip means the '
        'silent-data-corruption contract (docs/ROBUSTNESS.md '
        '"Integrity") stopped being exercised',
    'test_fleet_obs':
        'fleet observability tests (trace stitching, clock-offset '
        'alignment, merged metrics, federated flight recorder) run on '
        'the same localhost-TCP + forced-CPU stack as test_fleet, '
        'with no hardware dependency — a skip means the cross-process '
        'observability contract (docs/OBSERVABILITY.md "Fleet '
        'observability") stopped being exercised',
    'test_fproc_fast':
        'timestamped lut+fproc fabric tests run the fast engines on '
        'CPU (pallas via interpret mode) and the cores mesh on the '
        "conftest-forced 8-device host, with no hardware dependency — "
        'a skip means the feedback bit-identity contract '
        '(docs/PERF.md "Feedback on the fast engines") stopped being '
        'exercised',
    'test_qec_stream':
        'streaming-QEC tests (rounds scan vs sequential bit-identity, '
        'decoder fuzz vs the brute-force oracle, stream sessions '
        'surviving chaos kills) run on pure CPU with injected '
        'measurement planes, with no hardware dependency — a skip '
        'means the streaming contract (docs/SERVING.md "Streaming '
        'sessions", docs/PERF.md "Streaming QEC") stopped being '
        'exercised',
    'test_tenants':
        'tenant isolation tests (DRR fair queueing, admission quotas, '
        'usage metering, shed exemption, autoscale hysteresis) run on '
        'the forced CPU mesh + localhost sockets with no hardware '
        'dependency — a skip means the tenant-fairness contract '
        '(docs/SERVING.md "Tenants") stopped being exercised',
    'test_calib':
        'calibration tests (finite-difference gradient agreement, '
        'straight-through boundary behavior, closed serve-tier loops '
        'with live-qchip writeback and stale-epoch flush) run on pure '
        'CPU with no hardware dependency — a skip means the '
        'differentiable-physics contract (docs/CALIBRATION.md) '
        'stopped being exercised',
}

# the multi-device serve suite may skip ONLY on a genuinely
# single-device host: its module-level skip reason records how many
# devices the host advertised, and anything other than exactly one
# means the pool plumbing silently stopped being exercised (the
# serve-tier mirror of the pallas BAD SKIP gate above)
MULTIDEV_MODULE = 'test_serve_multidevice'
MULTIDEV_OK_SKIP = 'host advertises 1 device'

# the chaos suite proves the self-healing layer (supervision, retries,
# breaker quarantine, canary re-admission) under injected faults; it
# needs >= 2 virtual CPU devices, which the conftest always forces, so
# a skip with any reason other than a single-device host means the
# failure paths silently stopped being exercised
CHAOS_MODULE = 'test_serve_chaos'
CHAOS_OK_SKIP = 'host advertises 1 device'

# the observability suite (request tracing, metrics registry, flight
# recorder — docs/OBSERVABILITY.md) is pure CPU except the multi-hop
# chaos-trace test, which may skip only on a genuinely single-device
# host; anything else means the telemetry contract (frozen stats()
# manifest, span completeness, sampling-off cost) stopped being
# exercised
OBS_MODULE = 'test_obs'
OBS_OK_SKIP = 'host advertises 1 device'

# the ICI-fabric suite proves the cores-sharded interpreter (one
# program's core axis over the device mesh, sync/fproc riding
# all_gather collectives — docs/PERF.md "ICI fabric") bit-identical to
# the single-device generic engine; it needs >= 2 virtual CPU devices,
# which the conftest always forces, so a skip with any reason other
# than a genuinely single-device host means the cross-chip fabric
# silently stopped being exercised
ICI_MODULE = 'test_ici_fabric'
ICI_OK_SKIP = 'host advertises 1 device'


def _is_fault_test(tc) -> bool:
    ident = f'{tc.get("classname", "")}.{tc.get("name", "")}'.lower()
    return 'fault' in ident


def main(path: str) -> int:
    root = ET.parse(path).getroot()
    failures = root.findall('.//failure') + root.findall('.//error')
    if failures:
        for f in failures:
            print(f'FAILURE: {f.get("message", "")[:200]}')
        return 1
    n_tests = sum(int(s.get('tests', 0))
                  for s in root.iter('testsuite')) or int(
                      root.get('tests', 0))
    if n_tests == 0:
        print('FAILURE: no tests ran')
        return 1
    leaks, thread_leaks, bad_skips, dev_skips = [], [], [], []
    chaos_skips, obs_skips, ici_skips = [], [], []
    for tc in root.iter('testcase'):
        ident = f'{tc.get("classname")}.{tc.get("name")}'
        skipped = tc.find('skipped')
        if skipped is not None:
            for mod, why in NO_SKIP_MODULES.items():
                if mod in tc.get('classname', ''):
                    bad_skips.append((ident, why))
                    break
        if skipped is not None \
                and MULTIDEV_MODULE in tc.get('classname', ''):
            reason = (skipped.get('message') or '') + \
                (skipped.text or '')
            if MULTIDEV_OK_SKIP not in reason:
                dev_skips.append(ident)
        if skipped is not None \
                and CHAOS_MODULE in tc.get('classname', ''):
            reason = (skipped.get('message') or '') + \
                (skipped.text or '')
            if CHAOS_OK_SKIP not in reason:
                chaos_skips.append(ident)
        if skipped is not None \
                and OBS_MODULE in tc.get('classname', ''):
            reason = (skipped.get('message') or '') + \
                (skipped.text or '')
            if OBS_OK_SKIP not in reason:
                obs_skips.append(ident)
        if skipped is not None \
                and ICI_MODULE in tc.get('classname', ''):
            reason = (skipped.get('message') or '') + \
                (skipped.text or '')
            if ICI_OK_SKIP not in reason:
                ici_skips.append(ident)
        for out in (tc.findall('system-out') + tc.findall('system-err')):
            if not out.text:
                continue
            if FAULT_MARK in out.text and not _is_fault_test(tc) \
                    and ident not in leaks:
                leaks.append(ident)
            if LEAK_MARK in out.text and ident not in thread_leaks:
                thread_leaks.append(ident)
    if leaks:
        for name in leaks:
            print(f'FAULT LEAK: {name}: nonzero fault_shots from a '
                  f'non-fault-injection test (see docs/ROBUSTNESS.md)')
    if thread_leaks:
        for name in thread_leaks:
            print(f'THREAD LEAK: {name}: execution-service dispatcher '
                  f'thread survived the test (shut the service down — '
                  f'see docs/SERVING.md)')
    if bad_skips:
        for name, why in bad_skips:
            print(f'BAD SKIP: {name}: {why}')
    if dev_skips:
        for name in dev_skips:
            print(f'BAD SKIP: {name}: multi-device serve tests '
                  f'skipped on a host advertising >1 device — the '
                  f'executor pool stopped being exercised (see '
                  f'docs/SERVING.md "multi-device")')
    if chaos_skips:
        for name in chaos_skips:
            print(f'BAD SKIP: {name}: serve chaos tests skipped — the '
                  f'self-healing failure paths (retry/breaker/canary) '
                  f'stopped being exercised (see docs/ROBUSTNESS.md '
                  f'"serving-layer failures")')
    if obs_skips:
        for name in obs_skips:
            print(f'BAD SKIP: {name}: observability tests skipped — '
                  f'the tracing/metrics/flight-recorder contract '
                  f'stopped being exercised (see '
                  f'docs/OBSERVABILITY.md)')
    if ici_skips:
        for name in ici_skips:
            print(f'BAD SKIP: {name}: ICI-fabric tests skipped — the '
                  f'cores-sharded interpreter (cross-chip sync/fproc '
                  f'collectives) stopped being exercised (see '
                  f'docs/PERF.md "ICI fabric")')
    if leaks or thread_leaks or bad_skips or dev_skips or chaos_skips \
            or obs_skips or ici_skips:
        return 1
    print(f'junit OK: {n_tests} tests, no failures, no fault leaks, '
          f'no leaked service threads, no gated skips')
    return 0


if __name__ == '__main__':
    sys.exit(main(sys.argv[1]))
