#!/usr/bin/env python
"""Fault-injection fuzz driver: no injected defect may be SILENT.

Mutates valid machine programs (bit flips, truncated DONE, dropped
sync partners, starved fproc readers — fresh AND lut-feedback
fabrics — starved budgets, one-slot record budgets; see
``sim/faultinject.py``) and asserts every mutant is
rejected at decode, rejected by the static validator, trapped with a
correct ``fault_shots`` code by every engine that runs it, or provably
benign.  Also cross-checks the vmapped multi-program executable and
the dp=2 mesh-sharded sweep against per-program runs, the fused
measure-in-megastep engine against the generic engine on
physics-closed (sigma=0) runs for timing-independent fault codes, and
the serve-tier differential auditor (``audit_sample=1``) for
false-positive integrity violations across engine pairs, and the
generic / block / pallas(interpret) engines against each other on
lut+fproc feedback mutants (timestamped-fabric invariance,
docs/PERF.md "Feedback on the fast engines").

Deterministic in ``--seed``: a failing case name (``base+mutator#k``)
reproduces exactly.  Exit nonzero on any failure — wired into the
tier-1-adjacent CI flow via ``--quick``:

    python tools/faultfuzz.py --quick          # ~1 min, 56 mutants
    python tools/faultfuzz.py                  # full: >= 200 mutants
"""

import argparse
import os
import sys

# the mesh cross-check needs >= 2 devices; force a virtual 2-device CPU
# before jax initialises (a no-op when a real multi-device platform or
# the test conftest already configured one)
if 'JAX_PLATFORMS' not in os.environ:
    os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if '--xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=2').strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('--quick', action='store_true',
                    help='CI mode: 56 mutants, small vmap/mesh checks')
    ap.add_argument('-n', type=int, default=None,
                    help='mutant count (default 56 quick / 224 full)')
    ap.add_argument('--seed', type=int, default=0,
                    help='fuzz seed (every case is (seed, index)-'
                         'deterministic)')
    ap.add_argument('--no-mesh', action='store_true',
                    help='skip the dp=2 mesh cross-check')
    args = ap.parse_args(argv)
    n = args.n if args.n is not None else (56 if args.quick else 224)

    from distributed_processor_tpu.sim import faultinject as fi

    failed = False
    rep = fi.run_fuzz(
        seed=args.seed, n=n,
        progress=lambda r: print(f'  ... {r.n}/{n} mutants, '
                                 f'{len(r.failures)} failures',
                                 flush=True))
    print(f'fuzz: {rep.n} mutants -> '
          + ', '.join(f'{k}={v}' for k, v in sorted(rep.verdicts.items())))
    for name, verdict, detail in rep.failures:
        print(f'FAILURE: {name}: {verdict}: {detail}')
        failed = True

    bad = fi.check_vmap_consistency(seed=args.seed,
                                    n=4 if args.quick else 8)
    print(f'vmap cross-check: {bad} per-program mismatches')
    failed |= bad != 0

    # generic vs fused measure-in-megastep on timing-independent fault
    # codes (physics-closed at sigma=0; ineligible mutants are skipped)
    fr = fi.check_fused_consistency(seed=args.seed,
                                    n=24 if args.quick else 96)
    print(f'fused cross-check: {fr["checked"]} checked, '
          f'{fr["skipped"]} skipped, {len(fr["failures"])} failures')
    for name, detail in fr['failures']:
        print(f'FAILURE: {name}: {detail}')
    failed |= bool(fr['failures'])

    # generic vs block vs pallas(interpret) on lut+fproc feedback
    # mutants: the timestamped fabric admitted feedback to the fast
    # engines, so timing-independent fault codes must agree
    br = fi.check_feedback_consistency(seed=args.seed,
                                       n=12 if args.quick else 48)
    print(f'feedback cross-check: {br["checked"]} checked, '
          f'{br["skipped"]} skipped, {len(br["failures"])} failures')
    for name, detail in br['failures']:
        print(f'FAILURE: {name}: {detail}')
    failed |= bool(br['failures'])

    if not args.no_mesh:
        bad = fi.check_mesh_consistency(seed=args.seed,
                                        n=2 if args.quick else 4)
        if bad < 0:
            print('mesh cross-check: skipped (< 2 devices)')
        else:
            print(f'mesh cross-check: {bad} fault-stat mismatches')
            failed |= bad != 0

    # serve the corpus under audit_sample=1: the differential auditor
    # must never flag legitimately identical engines as corruption
    ar = fi.check_audit_consistency(seed=args.seed,
                                    n=16 if args.quick else 48)
    print(f'audit cross-check: {ar["checked"]} served, '
          f'{ar["skipped"]} skipped, {ar["audits"]} audits, '
          f'{ar["false_positives"]} false positives')
    failed |= ar['false_positives'] != 0

    print('faultfuzz ' + ('FAILED' if failed else 'OK'))
    return 1 if failed else 0


if __name__ == '__main__':
    sys.exit(main())
