#!/usr/bin/env python
"""Exec-phase overhead decomposition (round-3 weak #3, round-6 ladder).

The bench's exec phase (the batched interpreter while_loop, no resolve)
sits at ~18% of HBM peak; docs/PERF.md attributed the rest to
per-iteration fusion-boundary overhead without a measurement.  This
tool produces the measurement: timing the PURE exec phase (injected
bits — no physics, no resolve) across batch sizes decomposes the
per-step cost as

    t_batch = I * (a + b * B)

with I the interpreter steps: ``a`` is the per-iteration FIXED cost
(kernel launches, while-loop condition, carry aliasing — everything
that does not scale with shots) and ``b`` the per-shot streaming cost
(the carry-bytes HBM traffic).  The fixed fraction a/(a + b*B) at the
bench batch is the measured fusion-boundary budget.  A second sweep
re-times the same program with ``steps_per_iter`` unrolled k sub-steps
per iteration: overhead that amortizes with k is per-ITERATION
(recoverable by unrolling); what remains is per-STEP.

Round 6 extends the decomposition across the engine ladder
(:func:`decompose_engines`, imported by bench.py as the machine-
readable ``exec_profile`` artifact row): the same ``(a, b)`` fit per
engine, so the pallas megastep kernel's claim — it deletes fixed
per-step cost ``a``, not streaming cost ``b`` — is a measured number
(``fixed_cost_reduction_vs_generic``), not an assertion.  Each
engine's ``I`` is ITS outer-iteration count (instruction steps for
generic, while-loop trips for block/pallas), so totals
(``fixed_s_total = I * a``) are what compare across engines.

    python tools/exec_profile.py            # real chip

Env knobs: BENCH_QUBITS / BENCH_DEPTH (workload), PROFILE_BATCHES,
PROFILE_REPS, PROFILE_KS (unroll sweep), PROFILE_ENGINES (ladder
sweep, default 'generic,block,pallas'), PROFILE_PACKED / PROFILE_SL
(round-5 carry-layout levers, legacy sweep only).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json

import numpy as np

DEFAULT_BATCHES = (16384, 65536, 262144)
DEFAULT_ENGINES = ('generic', 'block', 'pallas')


def _fit(rows):
    """Least-squares ``t/I = a + b*B`` over ``(B, t, I)`` rows."""
    I = rows[0][2]
    A = np.array([[1.0, B] for B, _, _ in rows])
    y = np.array([t / I for _, t, _ in rows])
    (a, b), *_ = np.linalg.lstsq(A, y, rcond=None)
    return float(a), float(b), I


def _timed_run(mp, cfg, B, reps, rng):
    """Median warm wall-clock of one injected-bits batch + its exact
    outer-iteration count ('steps' counts while_loop trips; the span
    engine reports its unrolled instruction count)."""
    import jax
    from distributed_processor_tpu.sim.interpreter import simulate_batch
    bits = rng.integers(0, 2, size=(B, mp.n_cores, 2))
    out = simulate_batch(mp, bits, cfg=cfg)          # compile + warm
    jax.block_until_ready(out['steps'])
    steps = int(out['steps'])
    ts = []
    for _ in range(reps):
        bits = rng.integers(0, 2, size=(B, mp.n_cores, 2))
        t0 = time.perf_counter()
        out = simulate_batch(mp, bits, cfg=cfg)
        assert not bool(jax.block_until_ready(out['incomplete']))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), steps


def decompose_engines(n_qubits: int = 8, depth: int = 12,
                      batches=DEFAULT_BATCHES, reps: int = 3,
                      engines=DEFAULT_ENGINES) -> dict:
    """Per-engine ``(a, b)`` decomposition — the ``exec_profile`` row.

    Returns a machine-readable dict: per engine ``per_iter_fixed_s``
    (a), ``per_shot_s`` (b), ``iterations`` (I), ``fixed_s_total``
    (I*a — the cross-engine comparable), raw ``t_ms``; engines the
    program/backend cannot run record ``{'ineligible': reason}``
    instead of numbers.  Comparative ``fixed_cost_reduction_vs_generic``
    (generic I*a over this engine's I*a) is attached per non-generic
    engine that fit.
    """
    import jax
    from bench import build_machine_program
    from distributed_processor_tpu.sim.interpreter import (
        InterpreterConfig, resolve_engine)

    mp = build_machine_program(n_qubits, depth)
    base = dict(max_steps=2 * mp.n_instr + 64,
                max_pulses=int(mp.max_pulses_per_core(1)) + 4,
                max_meas=2, max_resets=2, record_pulses=False)
    out = {'platform': jax.devices()[0].platform,
           'n_qubits': n_qubits, 'depth': depth, 'n_instr': mp.n_instr,
           'batches': [int(B) for B in batches], 'reps': reps,
           'engines': {}}
    for eng in engines:
        cfg = InterpreterConfig(engine=eng, **base)
        try:
            resolve_engine(mp, cfg)
        except ValueError as e:
            out['engines'][eng] = {'ineligible': str(e)[:200]}
            continue
        rng = np.random.default_rng(0)
        rows = []
        for B in batches:
            t, steps = _timed_run(mp, cfg, int(B), reps, rng)
            rows.append((int(B), t, steps))
            print(f'{eng:>8} B={B:>7}: {t*1e3:8.2f} ms ({steps} iters)',
                  file=sys.stderr)
        a, b, I = _fit(rows)
        out['engines'][eng] = {
            'per_iter_fixed_s': a, 'per_shot_s': b, 'iterations': I,
            'fixed_s_total': a * I,
            'fixed_frac_at_largest_batch': round(
                a / (a + b * rows[-1][0]), 4) if a + b * rows[-1][0]
            else None,
            't_ms': {str(B): round(t * 1e3, 2) for B, t, _ in rows},
        }
    gen = out['engines'].get('generic', {})
    for eng, row in out['engines'].items():
        if eng != 'generic' and 'fixed_s_total' in row \
                and gen.get('fixed_s_total'):
            row['fixed_cost_reduction_vs_generic'] = round(
                gen['fixed_s_total'] / row['fixed_s_total'], 2) \
                if row['fixed_s_total'] else None
    # modeled megastep carry traffic: the unpacked vs bit-packed per-shot
    # bytes the 2*carry*steps exec-phase HBM model prices — the packed
    # layout's claimed reduction as a machine-readable number
    try:
        from distributed_processor_tpu.sim.interpreter import \
            carry_stream_bytes
        u, p = carry_stream_bytes(mp, InterpreterConfig(**base))
        out['carry_bytes_per_shot'] = {
            'unpacked': int(u), 'packed': int(p),
            'packed_reduction': round(u / p, 2) if p else None}
    except Exception as e:                          # non-span program etc.
        out['carry_bytes_per_shot'] = {
            'error': f'{type(e).__name__}: {e}'[:200]}
    return out


def main():
    import jax
    from bench import build_machine_program, enable_compilation_cache
    from distributed_processor_tpu.sim.interpreter import (
        InterpreterConfig)

    enable_compilation_cache()

    n_qubits = int(os.environ.get('BENCH_QUBITS', 8))
    depth = int(os.environ.get('BENCH_DEPTH', 12))
    reps = int(os.environ.get('PROFILE_REPS', 5))
    mp = build_machine_program(n_qubits, depth)
    base = dict(max_steps=2 * mp.n_instr + 64,
                max_pulses=int(mp.max_pulses_per_core(1)) + 4,
                max_meas=2, max_resets=2, record_pulses=False,
                # PROFILE_PACKED=1: packed [K, B, C] control carry
                # (InterpreterConfig.packed_ctrl) — round-5 lever (a)
                packed_ctrl=os.environ.get('PROFILE_PACKED') == '1',
                # PROFILE_SL=1: emitted straight-line executor — round-5
                # lever (b)
                straightline=(None if os.environ.get('PROFILE_SL') == '1'
                              else False))
    rng = np.random.default_rng(0)

    def timed(B, k):
        cfg = InterpreterConfig(steps_per_iter=k, **base)
        return _timed_run(mp, cfg, B, reps, rng)

    result = {'platform': jax.devices()[0].platform,
              'device': str(jax.devices()[0]),
              'n_instr': mp.n_instr, 'reps': reps}

    # 1. t(B) decomposition at k=1
    batches = [int(x) for x in os.environ.get(
        'PROFILE_BATCHES', ','.join(map(str, DEFAULT_BATCHES)))
        .split(',')]
    rows = []
    for B in batches:
        t, steps = timed(B, 1)
        rows.append((B, t, steps))
        print(f'B={B:>7} k=1: {t*1e3:8.2f} ms  ({steps} steps)',
              file=sys.stderr)
    a, b, I = _fit(rows)
    B_bench = batches[-1]
    fixed_frac = a / (a + b * B_bench)
    result['per_step_fixed_s'] = float(a)
    result['per_step_per_shot_s'] = float(b)
    result['steps'] = I
    result['fixed_frac_at_bench_batch'] = round(float(fixed_frac), 4)
    result['t_ms'] = {str(B): round(t * 1e3, 2) for B, t, _ in rows}

    # 2. unroll sweep at the bench batch: does the fixed cost amortize?
    ks = [int(x) for x in os.environ.get('PROFILE_KS', '1,2,4,8')
          .split(',')]
    result['unroll_t_ms'] = {}
    for k in ks:
        t, _ = timed(B_bench, k)
        result['unroll_t_ms'][str(k)] = round(t * 1e3, 2)
        print(f'B={B_bench} k={k}: {t*1e3:8.2f} ms', file=sys.stderr)

    # 3. unroll sweep at a small batch (fixed cost dominates there, so
    # any per-iteration amortization shows up amplified)
    result['unroll_small_t_ms'] = {}
    for k in ks:
        t, _ = timed(batches[0], k)
        result['unroll_small_t_ms'][str(k)] = round(t * 1e3, 2)
        print(f'B={batches[0]} k={k}: {t*1e3:8.2f} ms', file=sys.stderr)

    # 4. engine-ladder decomposition (the bench's exec_profile row)
    engines = tuple(os.environ.get(
        'PROFILE_ENGINES', ','.join(DEFAULT_ENGINES)).split(','))
    result['engine_ladder'] = decompose_engines(
        n_qubits, depth, batches=batches, reps=reps, engines=engines)

    print(json.dumps(result))


if __name__ == '__main__':
    main()
