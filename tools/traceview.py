#!/usr/bin/env python
"""Per-stage latency waterfall from an exported Chrome trace.

Reads the Chrome Trace Event JSON written by
``ExecutionService.dump_trace`` / ``cli serve-bench --trace-out`` /
``tools/servechaos.py --trace-out`` and summarizes the request
lifecycle stage by stage: for every duration span name (queued,
compile, coalesce.ripen, dispatch, execute, demux, ...) the count,
p50/p99/max milliseconds, and the share of total traced time — the
five-second answer to "where does my p99 live?" without opening
Perfetto.  Instant events (retries, steals, migrations, chaos
injections, ...) are tallied by name below the waterfall.

Also wired as ``python -m distributed_processor_tpu.cli trace-view``.

    python tools/traceview.py trace.json
    python tools/traceview.py trace.json --json
"""

import argparse
import json
import sys

# canonical lifecycle order (obs.trace.STAGE_ORDER); stages absent
# from a trace are skipped, names outside it sort after, alphabetical
STAGE_ORDER = ('submit', 'submit_source', 'compile', 'queued',
               'coalesce.ripen', 'dispatch', 'execute', 'demux')


def _pct(sorted_vals, p):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(p / 100.0 * len(sorted_vals)))
    return sorted_vals[i]


def summarize(path: str) -> dict:
    """Stage waterfall + instant tallies for one Chrome-trace file."""
    with open(path, 'r', encoding='utf-8') as f:
        doc = json.load(f)
    events = doc.get('traceEvents', [])
    durs = {}       # name -> [dur_ms, ...]
    instants = {}   # name -> count
    requests = set()
    for e in events:
        requests.add(e.get('tid'))
        name = e.get('name', '?')
        if e.get('ph') == 'X':
            durs.setdefault(name, []).append(e.get('dur', 0) / 1e3)
        elif e.get('ph') == 'i':
            instants[name] = instants.get(name, 0) + 1
    total_ms = sum(sum(v) for v in durs.values())
    rank = {n: i for i, n in enumerate(STAGE_ORDER)}
    stages = []
    for name in sorted(durs, key=lambda n: (rank.get(n, len(rank)), n)):
        vals = sorted(durs[name])
        stage_ms = sum(vals)
        stages.append({
            'stage': name,
            'count': len(vals),
            'p50_ms': round(_pct(vals, 50), 3),
            'p99_ms': round(_pct(vals, 99), 3),
            'max_ms': round(vals[-1], 3),
            'total_ms': round(stage_ms, 3),
            'share': round(stage_ms / total_ms, 4) if total_ms else 0.0,
        })
    return {
        'path': path,
        'events': len(events),
        'requests': len(requests),
        'stages': stages,
        'instants': dict(sorted(instants.items())),
    }


def format_table(summary: dict) -> str:
    lines = [f"{summary['path']}: {summary['events']} events, "
             f"{summary['requests']} traced request(s)", '']
    hdr = (f"{'stage':>16} {'count':>6} {'p50_ms':>9} {'p99_ms':>9} "
           f"{'max_ms':>9} {'total_ms':>10} {'share':>6}")
    lines.append(hdr)
    lines.append('-' * len(hdr))
    for s in summary['stages']:
        lines.append(f"{s['stage']:>16} {s['count']:>6} "
                     f"{s['p50_ms']:>9.3f} {s['p99_ms']:>9.3f} "
                     f"{s['max_ms']:>9.3f} {s['total_ms']:>10.3f} "
                     f"{s['share']:>6.1%}")
    if summary['instants']:
        lines.append('')
        lines.append('events: ' + '  '.join(
            f'{k}={v}' for k, v in summary['instants'].items()))
    return '\n'.join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('trace', help='Chrome Trace Event JSON '
                                  '(ExecutionService.dump_trace output)')
    ap.add_argument('--json', action='store_true',
                    help='emit the summary as JSON instead of a table')
    args = ap.parse_args(argv)
    try:
        summary = summarize(args.trace)
    except (OSError, ValueError) as e:
        print(f'traceview: cannot read {args.trace}: {e}',
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(format_table(summary))
    return 0


if __name__ == '__main__':
    sys.exit(main())
