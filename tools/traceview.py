#!/usr/bin/env python
"""Per-stage latency waterfall from an exported Chrome trace.

Reads the Chrome Trace Event JSON written by
``ExecutionService.dump_trace`` / ``FleetRouter.dump_trace`` /
``cli serve-bench --trace-out`` / ``tools/servechaos.py --trace-out``
and summarizes the request lifecycle stage by stage: for every
duration span name (route, wire.send, queued, compile, coalesce.ripen,
dispatch, execute, demux, wire.await, ...) the count, p50/p99/max
milliseconds, the share of total traced time, and — for fleet traces —
the per-hop wire time (p50 of the ``wire_ms`` arg the router stamps on
``wire.await`` spans: round trip minus the replica-observed window).
The five-second answer to "where does my p99 live?" without opening
Perfetto.  Instant events (retries, failovers, steals, chaos
injections, ...) are tallied by name below the waterfall.

Empty or invalid trace files (no JSON object, no ``traceEvents``) are
an error: ``summarize`` raises ``ValueError`` and the CLI exits 1 with
the reason — a silent empty waterfall reads as "zero latency".

Also wired as ``python -m distributed_processor_tpu.cli trace-view``.

    python tools/traceview.py trace.json
    python tools/traceview.py trace.json --json
"""

import argparse
import json
import sys

# canonical lifecycle order (obs.trace.STAGE_ORDER); stages absent
# from a trace are skipped, names outside it sort after, alphabetical
STAGE_ORDER = ('submit', 'submit_source', 'route', 'wire.send',
               'compile', 'queued', 'coalesce.ripen', 'dispatch',
               'execute', 'demux', 'wire.await')


def _pct(sorted_vals, p):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1,
            int(p / 100.0 * len(sorted_vals)))
    return sorted_vals[i]


def summarize(path: str) -> dict:
    """Stage waterfall + instant tallies for one Chrome-trace file.

    Raises ``ValueError`` when the file is not a Chrome Trace Event
    document or contains no events — an empty waterfall must never
    pass for a measured one."""
    with open(path, 'r', encoding='utf-8') as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f'{path}: not valid JSON: {e}') from e
    if not isinstance(doc, dict):
        raise ValueError(
            f'{path}: expected a Chrome Trace object with '
            f'"traceEvents", got {type(doc).__name__}')
    events = doc.get('traceEvents')
    if not isinstance(events, list):
        raise ValueError(f'{path}: no "traceEvents" array — not a '
                         f'Chrome Trace Event file')
    if not events:
        raise ValueError(f'{path}: trace contains zero events '
                         f'(was tracing enabled? --trace-sample > 0)')
    durs = {}       # name -> [dur_ms, ...]
    wires = {}      # name -> [args.wire_ms, ...] (fleet wire.await)
    instants = {}   # name -> count
    requests = set()
    processes = set()
    for e in events:
        requests.add(e.get('tid'))
        processes.add(e.get('pid'))
        name = e.get('name', '?')
        if e.get('ph') == 'X':
            durs.setdefault(name, []).append(e.get('dur', 0) / 1e3)
            w = (e.get('args') or {}).get('wire_ms')
            if w is not None:
                wires.setdefault(name, []).append(float(w))
        elif e.get('ph') == 'i':
            instants[name] = instants.get(name, 0) + 1
    total_ms = sum(sum(v) for v in durs.values())
    rank = {n: i for i, n in enumerate(STAGE_ORDER)}
    stages = []
    for name in sorted(durs, key=lambda n: (rank.get(n, len(rank)), n)):
        vals = sorted(durs[name])
        stage_ms = sum(vals)
        row = {
            'stage': name,
            'count': len(vals),
            'p50_ms': round(_pct(vals, 50), 3),
            'p99_ms': round(_pct(vals, 99), 3),
            'max_ms': round(vals[-1], 3),
            'total_ms': round(stage_ms, 3),
            'share': round(stage_ms / total_ms, 4) if total_ms else 0.0,
        }
        if name in wires:
            # pure wire + queueing cost of the hop, separated from the
            # replica-side work the span's duration also covers
            row['wire_p50_ms'] = round(_pct(sorted(wires[name]), 50), 3)
        stages.append(row)
    return {
        'path': path,
        'events': len(events),
        'requests': len(requests),
        'processes': len(processes),
        'stages': stages,
        'instants': dict(sorted(instants.items())),
    }


def format_table(summary: dict) -> str:
    lines = [f"{summary['path']}: {summary['events']} events, "
             f"{summary['requests']} traced request(s), "
             f"{summary.get('processes', 1)} process row(s)", '']
    has_wire = any('wire_p50_ms' in s for s in summary['stages'])
    hdr = (f"{'stage':>16} {'count':>6} {'p50_ms':>9} {'p99_ms':>9} "
           f"{'max_ms':>9} {'total_ms':>10} {'share':>6}")
    if has_wire:
        hdr += f" {'wire_p50':>9}"
    lines.append(hdr)
    lines.append('-' * len(hdr))
    for s in summary['stages']:
        row = (f"{s['stage']:>16} {s['count']:>6} "
               f"{s['p50_ms']:>9.3f} {s['p99_ms']:>9.3f} "
               f"{s['max_ms']:>9.3f} {s['total_ms']:>10.3f} "
               f"{s['share']:>6.1%}")
        if has_wire:
            row += (f" {s['wire_p50_ms']:>9.3f}"
                    if 'wire_p50_ms' in s else f" {'':>9}")
        lines.append(row)
    if summary['instants']:
        lines.append('')
        lines.append('events: ' + '  '.join(
            f'{k}={v}' for k, v in summary['instants'].items()))
    return '\n'.join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument('trace', help='Chrome Trace Event JSON '
                                  '(ExecutionService.dump_trace output)')
    ap.add_argument('--json', action='store_true',
                    help='emit the summary as JSON instead of a table')
    args = ap.parse_args(argv)
    try:
        summary = summarize(args.trace)
    except (OSError, ValueError) as e:
        print(f'traceview: cannot read {args.trace}: {e}',
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(format_table(summary))
    return 0


if __name__ == '__main__':
    sys.exit(main())
